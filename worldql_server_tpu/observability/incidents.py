"""Incident recorder: one correlated capsule per burn, debounced.

When an SLO objective transitions into ``BURNING`` the evidence for
*why* is scattered across subsystems — the flight recorder has the slow
spans, the governor knows what it shed, placement knows whether a
migration was in flight, the interest ledger knows who resynced, device
telemetry knows whether a retrace storm hit, and the failpoint registry
knows what chaos was armed.  This module captures all of it in ONE
JSON bundle the moment the burn starts (debounced by
``--incident-cooldown`` so a flapping objective yields exactly one
capsule per cooldown window), written into a bounded ring of files
under ``--incident-dir`` and listed/fetchable at ``GET
/debug/incidents``.

The capsule's shape::

    {
      "id": "incident-0001-frame_e2e_p99",
      "at_unix_s": ...,
      "objective": {<triggering objective status>},
      "trajectory": [{t, burn_fast, burn_slow, level}, ...],
      "slo": {<full /debug/slo report at capture time>},
      "sections": {<this process's subsystem sections>},
      "shards": {"0": {<shard dump incl. its sections>}, ...}  # router only
    }

:func:`capsule_sections` is the ONE place that knows how to pull a
process's subsystem state; the shard dump op embeds the same sections
so the router's fleet capsule (pulled over the PR 15 chunked control
path — the same helper ``GET /debug/cluster`` uses) carries every
process's view without a second snapshot protocol.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import re
import time
from typing import Any, Awaitable, Callable

from ..robustness import failpoints

log = logging.getLogger("worldql.incidents")

#: Bounded ring: newest N capsules are kept on disk, older deleted.
DEFAULT_KEEP = 16

_FILE_RE = re.compile(r"^incident-(\d{4})-([A-Za-z0-9_]+)\.json$")


def top_stage_attribution(recorder, n: int = 3) -> list[tuple[str, float]]:
    """Top-``n`` (stage, ms) pairs from the flight recorder's worst
    tick — what the CRITICAL incident log names as the likely culprits.
    Degrades to ``[]`` when tracing is off or nothing is recorded."""
    if recorder is None:
        return []
    try:
        worst = recorder.worst_tick()
        if worst is None:
            return []
        stages = worst.stage_ms()
    except Exception:  # noqa: BLE001 — attribution is best-effort
        log.exception("incident stage attribution failed")
        return []
    ranked = sorted(stages.items(), key=lambda kv: kv[1], reverse=True)
    return [(name, round(ms, 2)) for name, ms in ranked[:n]]


def capsule_sections(server) -> dict:
    """Every subsystem section this process can contribute to a
    capsule.  Sections for disabled subsystems report ``enabled: False``
    instead of vanishing so a capsule's shape is stable and a reader
    can tell "off" from "lost".  Each probe is fenced: one broken
    subsystem must not cost the rest of the evidence."""
    sections: dict[str, Any] = {}

    def probe(name: str, fn: Callable[[], Any]) -> None:
        try:
            sections[name] = fn()
        except Exception:  # noqa: BLE001
            log.exception("incident section %r probe failed", name)
            sections[name] = {"error": "probe failed"}

    recorder = getattr(server, "recorder", None)
    if recorder is not None:
        probe("flight_recorder", lambda: {
            "stats": recorder.stats(),
            "ticks": recorder.snapshot(),
            "loose": recorder.loose_snapshot(),
            "top_stages": top_stage_attribution(recorder),
        })
    else:
        sections["flight_recorder"] = {"enabled": False}

    governor = getattr(server, "governor", None)
    if governor is not None:
        probe("governor", lambda: {
            "status": governor.status(),
            "export": governor.export_state(),
        })
    else:
        sections["governor"] = {"enabled": False}

    cluster = getattr(server, "cluster", None)
    if cluster is not None:
        probe("placement", lambda: {
            "epoch": cluster.placement.epoch,
            "stats": cluster.stats(),
        })
    else:
        sections["placement"] = {"enabled": False, "epoch": 0}

    interest = getattr(server, "interest", None)
    if interest is not None:
        probe("interest", interest.stats)
    else:
        sections["interest"] = {"enabled": False}

    telemetry = getattr(server, "device_telemetry", None)
    if telemetry is not None:
        probe("device", telemetry.stats)
    else:
        sections["device"] = {"enabled": False}

    monitor = getattr(server, "loop_monitor", None)
    if monitor is not None:
        probe("loop_health", monitor.snapshot)
    else:
        sections["loop_health"] = {"enabled": False}

    probe("failpoints", lambda: dict(failpoints.registry.fired_counts()))
    return sections


class IncidentRecorder:
    """Debounced capsule writer over a bounded on-disk ring.

    ``collect`` (set by the owning process) is an async callable
    returning the capsule body — everything beyond the id/timestamp/
    trigger envelope.  The single-process server collects locally; the
    router additionally pulls every shard's dump over the shared
    chunked-control client so the fleet capsule and ``/debug/cluster``
    cannot drift apart."""

    def __init__(
        self,
        incident_dir: str,
        *,
        cooldown_s: float = 60.0,
        keep: int = DEFAULT_KEEP,
        metrics=None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.dir = incident_dir
        self.cooldown_s = float(cooldown_s)
        self.keep = int(keep)
        self.metrics = metrics
        self.clock = clock
        self.collect: Callable[[], Awaitable[dict]] | None = None
        self.captured = 0
        self.suppressed = 0
        self.errors = 0
        self._last_capture_t: float | None = None
        self._seq = self._scan_seq()
        self._tasks: set[asyncio.Task] = set()

    # -- trigger + debounce -----------------------------------------

    def trigger(self, objective, slo_status: dict) -> bool:
        """Called from the SLO eval loop on a transition into BURNING.
        Returns True when a capture task was actually started (one per
        cooldown window)."""
        now = self.clock()
        if (
            self._last_capture_t is not None
            and now - self._last_capture_t < self.cooldown_s
        ):
            self.suppressed += 1
            if self.metrics is not None:
                self.metrics.inc("incidents.suppressed")
            return False
        self._last_capture_t = now
        task = asyncio.get_running_loop().create_task(
            self._capture(objective, slo_status),
            name=f"incident-{objective.name}",
        )
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return True

    async def _capture(self, objective, slo_status: dict) -> None:
        self._seq += 1
        incident_id = f"incident-{self._seq:04d}-{objective.name}"
        capsule: dict[str, Any] = {
            "id": incident_id,
            "at_unix_s": round(time.time(), 6),
            "objective": {"name": objective.name, **objective.status()},
            "trajectory": list(objective.trajectory),
            "slo": slo_status,
        }
        top = []
        try:
            if self.collect is not None:
                body = await self.collect()
                if isinstance(body, dict):
                    capsule.update(body)
                    sec = body.get("sections")
                    if isinstance(sec, dict):
                        top = (sec.get("flight_recorder") or {}).get(
                            "top_stages") or []
        except Exception:  # noqa: BLE001
            self.errors += 1
            if self.metrics is not None:
                self.metrics.inc("incidents.errors")
            log.exception("incident %s: collect failed", incident_id)
            capsule["collect_error"] = True
        try:
            os.makedirs(self.dir, exist_ok=True)
            path = os.path.join(self.dir, f"{incident_id}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(capsule, fh, default=repr)
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001
            self.errors += 1
            if self.metrics is not None:
                self.metrics.inc("incidents.errors")
            log.exception("incident %s: capsule write failed", incident_id)
            return
        self.captured += 1
        if self.metrics is not None:
            self.metrics.inc("incidents.captured")
        self._prune()
        log.critical(
            "SLO INCIDENT %s: objective %s BURNING "
            "(burn fast=%.2f slow=%.2f, budget_remaining=%.2f) — "
            "top stages %s — capsule %s",
            incident_id, objective.name,
            objective.burn_fast, objective.burn_slow,
            objective.budget_remaining,
            [f"{name}={ms}ms" for name, ms in top] or "<no trace>",
            path,
        )

    # -- ring maintenance -------------------------------------------

    def _scan_seq(self) -> int:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return 0
        seqs = [int(m.group(1)) for n in names if (m := _FILE_RE.match(n))]
        return max(seqs, default=0)

    def _entries(self) -> list[tuple[int, str, str]]:
        """(seq, objective, filename) for every capsule on disk."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        out = []
        for n in names:
            m = _FILE_RE.match(n)
            if m:
                out.append((int(m.group(1)), m.group(2), n))
        out.sort()
        return out

    def _prune(self) -> None:
        entries = self._entries()
        for seq, _obj, name in entries[: max(0, len(entries) - self.keep)]:
            try:
                os.unlink(os.path.join(self.dir, name))
            except OSError:
                log.warning("incident prune: could not delete %s", name)

    # -- introspection (HTTP surface) -------------------------------

    def list(self) -> list[dict]:
        out = []
        for seq, obj, name in self._entries():
            entry = {
                "id": name[: -len(".json")],
                "seq": seq,
                "objective": obj,
            }
            try:
                entry["bytes"] = os.path.getsize(os.path.join(self.dir, name))
            except OSError:
                pass
            out.append(entry)
        return out

    def load(self, incident_id: str) -> dict | None:
        if not _FILE_RE.match(incident_id + ".json"):
            return None
        path = os.path.join(self.dir, incident_id + ".json")
        try:
            with open(path, encoding="utf-8") as fh:
                return json.load(fh)
        except OSError:
            return None

    def stats(self) -> dict:
        return {
            "captured": self.captured,
            "suppressed": self.suppressed,
            "errors": self.errors,
            "cooldown_s": self.cooldown_s,
            "keep": self.keep,
            "on_disk": len(self._entries()),
        }

    async def drain(self) -> None:
        """Await in-flight capture tasks (teardown)."""
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)
