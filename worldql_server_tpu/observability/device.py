"""Device telemetry: compile/retrace events + per-tick timing split.

ROADMAP item 2 ("engine p99 < 5 ms *by measurement*") needs three
things no aggregate histogram provides: WHICH kernel recompiled and
when (a retrace mid-serving is tens of ms to seconds inside a 5 ms
budget — the failure mode the utils/retrace.py GUARD exists for),
WHERE a tick's wall went between host encode / transfer / device
compute / fetch, and how much device memory the index is pinning.
This module is the bridge between those device-side facts and the
PR 5 observability substrate:

* **Compile events** — a ``jax.monitoring`` duration listener counts
  every backend compile (``device.compiles`` counter +
  ``device.compile_ms`` histogram). The listener is module-global and
  fans out to the live :class:`DeviceTelemetry` instances (jax's
  listener list is append-only — there is no unregister — so instances
  attach/detach from a shared set instead).
* **Retrace attribution** — :meth:`DeviceTelemetry.poll_retraces`
  diffs the retrace GUARD's per-family compiled-variant counts; any
  growth emits a ``device.retraces`` counter increment and a NAMED
  loose span (``device.retrace``) into the flight recorder, tagged
  with the kernel family, the capacity tier of the last dispatch (a
  tier first-hit is the expected trigger) and the compile wall drained
  from the listener since the last poll. The tick batcher polls once
  per collect, so a mid-serving retrace surfaces the same tick it
  happened.
* **Per-tick device split** — :meth:`on_tick` tags the tick root trace
  with the backend's ``last_device_timing`` (encode_ms / h2d_ms /
  compute_ms / d2h_ms, host-side brackets of the dispatch/collect
  instrumentation points — see spatial/tpu_backend.py) and feeds the
  ``device.{encode,h2d,compute,d2h}_ms`` histograms.
* **Live buffer gauge** — :func:`live_device_bytes` sums live jax
  array footprints at scrape time (the ``device`` gauge), without ever
  importing jax on its own: a CPU-backend server that never loaded jax
  reports 0.
"""

from __future__ import annotations

import logging
import sys
import threading

from ..utils.retrace import GUARD

logger = logging.getLogger(__name__)

#: the backend-compile duration event jax 0.4.x emits once per XLA
#: compilation (jaxpr tracing / MLIR lowering emit their own events —
#: the backend compile is the expensive leg and the one-per-variant
#: signal the retrace accounting wants)
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_active_lock = threading.Lock()
_active: set = set()
_listener_installed = False


def _dispatch_event(event: str, duration_secs: float, **_kw) -> None:
    if event != COMPILE_EVENT:
        return
    with _active_lock:
        sinks = list(_active)
    for tel in sinks:
        tel._on_compile(duration_secs)


def _ensure_listener() -> bool:
    """Register the module-global jax.monitoring listener once.
    Returns False when jax is unavailable (pure-CPU minimal builds) —
    telemetry then degrades to GUARD polling without compile walls."""
    global _listener_installed
    if _listener_installed:
        return True
    try:
        import jax.monitoring
    except Exception:
        return False
    jax.monitoring.register_event_duration_secs_listener(_dispatch_event)
    _listener_installed = True
    return True


def live_device_bytes() -> int:
    """Total bytes of live jax arrays RIGHT NOW (0 when jax was never
    imported — this probe must not force device bring-up). Pull-gauge
    cost only: evaluated per /metrics scrape, never on the tick path."""
    if "jax" not in sys.modules:
        return 0
    try:
        import jax

        return sum(
            int(getattr(a, "nbytes", 0) or 0) for a in jax.live_arrays()
        )
    except Exception:
        return 0


class DeviceTelemetry:
    """Per-server device telemetry hub (one per WorldQLServer; the
    bench builds its own around a bare backend)."""

    def __init__(self, metrics=None, tracer=None, backend=None):
        self.metrics = metrics
        self.tracer = tracer
        self.backend = backend
        self._lock = threading.Lock()
        self._pending_compile_ms = 0.0   # drained by the next poll
        self.compiles = 0
        self.compile_ms_total = 0.0
        self.retraces = 0
        # baseline at construction: warmup compiles that happened
        # before telemetry existed are not "retraces"
        self._guard_last = GUARD.counts()

    # region: lifecycle

    def install(self) -> "DeviceTelemetry":
        with _active_lock:
            _active.add(self)
        if not _ensure_listener():
            logger.info(
                "jax.monitoring unavailable — compile walls will not be "
                "attributed (retrace counting still active)"
            )
        return self

    def uninstall(self) -> None:
        with _active_lock:
            _active.discard(self)

    # endregion

    # region: compile events (listener thread — may be any thread)

    def _on_compile(self, duration_secs: float) -> None:
        ms = duration_secs * 1e3
        with self._lock:
            self.compiles += 1
            self.compile_ms_total += ms
            self._pending_compile_ms += ms
        if self.metrics is not None:
            self.metrics.inc("device.compiles")
            self.metrics.observe_ms("device.compile_ms", ms)

    def _drain_compile_ms(self) -> float:
        with self._lock:
            ms, self._pending_compile_ms = self._pending_compile_ms, 0.0
        return ms

    # endregion

    # region: retrace polling

    def poll_retraces(self) -> dict:
        """Diff the retrace GUARD since the last poll; every family
        that gained compiled variants emits a counter increment and a
        named loose span (flight-recorder visible). Returns the delta
        (tests pin it). Cost when nothing changed: one small dict
        compare — safe once per tick."""
        counts = GUARD.counts()
        last = self._guard_last
        delta = {
            family: grown
            for family, count in counts.items()
            if (grown := count - last.get(family, 0)) > 0
        }
        self._guard_last = counts
        if not delta:
            return delta
        compile_ms = self._drain_compile_ms()
        tier = dict(getattr(self.backend, "last_dispatch_tier", None) or {})
        for family, grown in delta.items():
            self.retraces += grown
            if self.metrics is not None:
                self.metrics.inc("device.retraces", grown)
            if self.tracer is not None and self.tracer.enabled:
                # a loose single-span trace: rides the flight
                # recorder's loose ring next to router handles/fsyncs
                with self.tracer.span(
                    "device.retrace", family=family, new_variants=grown,
                    compile_ms=round(compile_ms, 3), **tier,
                ):
                    pass
            logger.warning(
                "jit retrace: %s +%d variant(s) (compile %.1f ms, "
                "tier %s) — a hot-path kernel recompiled mid-serving",
                family, grown, compile_ms, tier or "?",
            )
        return delta

    # endregion

    # region: per-tick hook (called by TickBatcher._note_collect_stats)

    def on_tick(self, trace) -> None:
        timing = getattr(self.backend, "last_device_timing", None)
        if timing:
            trace.tag(device_timing={
                k: (round(v, 3) if isinstance(v, float) else v)
                for k, v in timing.items()
            })
            if self.metrics is not None:
                for leg in ("encode_ms", "h2d_ms", "compute_ms", "d2h_ms"):
                    value = timing.get(leg)
                    if isinstance(value, (int, float)):
                        self.metrics.observe_ms(
                            f"device.{leg}", max(float(value), 0.0)
                        )
        self.poll_retraces()

    # endregion

    def stats(self) -> dict:
        """The ``device`` pull gauge: compile/retrace totals + the live
        device-buffer footprint."""
        return {
            "compiles": self.compiles,
            "retraces": self.retraces,
            "compile_ms_total": round(self.compile_ms_total, 3),
            "buffer_bytes": live_device_bytes(),
        }
