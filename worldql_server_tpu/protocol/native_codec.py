"""ctypes bridge to the native C++ wire codec (native/codec.cpp).

Loads ``native/libwqlcodec.so`` if it has been built (``make -C
native``); otherwise ``load()`` returns None and the protocol package
stays on the pure-Python codec — same semantics, slower. The reference
pays this cost differently: its codec is compiled Rust behind a global
serializer mutex (structures/message.rs:116-134); here the native path
is re-entrant and per-call.

Message-level semantics (missing-field errors, Instruction/Replication
catch-alls, UUID parsing) stay in Python — the C layer only moves
bytes. Messages with more than ``WQL_MAX_OBJS`` records/entities fall
back to the Python codec transparently.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
import uuid as uuid_mod
from pathlib import Path

from .types import Entity, Instruction, Message, Record, Replication, Vector3

logger = logging.getLogger(__name__)

_LIB_PATH = Path(__file__).resolve().parent.parent.parent / "native" / "libwqlcodec.so"

MAX_OBJS = 1024


class _WqlObj(ctypes.Structure):
    _fields_ = [
        ("uuid", ctypes.c_void_p), ("uuid_len", ctypes.c_int32),
        ("world", ctypes.c_void_p), ("world_len", ctypes.c_int32),
        ("data", ctypes.c_void_p), ("data_len", ctypes.c_int32),
        ("flex", ctypes.c_void_p), ("flex_len", ctypes.c_int32),
        ("x", ctypes.c_double), ("y", ctypes.c_double), ("z", ctypes.c_double),
        ("has_pos", ctypes.c_uint8),
    ]


class _WqlMsg(ctypes.Structure):
    _fields_ = [
        ("instruction", ctypes.c_uint8),
        ("replication", ctypes.c_uint8),
        ("has_pos", ctypes.c_uint8),
        ("x", ctypes.c_double), ("y", ctypes.c_double), ("z", ctypes.c_double),
        ("parameter", ctypes.c_void_p), ("parameter_len", ctypes.c_int32),
        ("sender", ctypes.c_void_p), ("sender_len", ctypes.c_int32),
        ("world", ctypes.c_void_p), ("world_len", ctypes.c_int32),
        ("flex", ctypes.c_void_p), ("flex_len", ctypes.c_int32),
        ("n_records", ctypes.c_int32),
        ("n_entities", ctypes.c_int32),
        ("records", _WqlObj * MAX_OBJS),
        ("entities", _WqlObj * MAX_OBJS),
    ]


def _view(ptr, length: int) -> bytes | None:
    if not ptr:
        return None
    return ctypes.string_at(ptr, length)


def _text(ptr, length: int) -> str | None:
    raw = _view(ptr, length)
    return None if raw is None else raw.decode("utf-8")


class NativeCodec:
    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.wql_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(_WqlMsg)
        ]
        lib.wql_decode.restype = ctypes.c_int
        lib.wql_encode.argtypes = [
            ctypes.POINTER(_WqlMsg),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.wql_encode.restype = ctypes.c_int
        lib.wql_buffer_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.wql_buffer_free.restype = None
        lib.wql_max_objs.argtypes = []
        lib.wql_max_objs.restype = ctypes.c_int
        # Reusable scratch, one per thread: the ~128 KB _WqlMsg would be
        # wasteful to allocate per call, and sharing one across threads
        # would interleave half-populated messages.
        self._tls = threading.local()

    @property
    def _scratch(self) -> _WqlMsg:
        scratch = getattr(self._tls, "msg", None)
        if scratch is None:
            scratch = self._tls.msg = _WqlMsg()
        return scratch

    # region: decode

    def decode(self, data: bytes, errcls: type[Exception]) -> Message:
        try:
            return self._decode(data, errcls)
        except (errcls, _TooManyObjects):
            raise
        except Exception as exc:  # e.g. invalid UTF-8 → typed error
            raise errcls(f"invalid flatbuffer: {exc}") from exc

    def _decode(self, data: bytes, errcls: type[Exception]) -> Message:
        msg = self._scratch
        rc = self._lib.wql_decode(data, len(data), ctypes.byref(msg))
        if rc == -2:  # WQL_E_TOO_MANY → caller falls back to Python codec
            raise _TooManyObjects()
        if rc != 0:
            raise errcls(f"invalid flatbuffer (native rc {rc})")

        sender = _text(msg.sender, msg.sender_len)
        if sender is None:
            raise errcls("missing required field: sender_uuid")
        world = _text(msg.world, msg.world_len)
        if world is None:
            raise errcls("missing required field: world_name")
        try:
            sender_uuid = uuid_mod.UUID(sender)
        except ValueError as exc:
            raise errcls(f"invalid sender uuid: {exc}") from exc

        return Message(
            instruction=Instruction.from_wire(msg.instruction),
            parameter=_text(msg.parameter, msg.parameter_len),
            sender_uuid=sender_uuid,
            world_name=world,
            replication=Replication.from_wire(msg.replication),
            records=[
                self._decode_obj(msg.records[i], Record, errcls)
                for i in range(msg.n_records)
            ],
            entities=[
                self._decode_obj(msg.entities[i], Entity, errcls)
                for i in range(msg.n_entities)
            ],
            position=(
                Vector3(msg.x, msg.y, msg.z) if msg.has_pos else None
            ),
            flex=_view(msg.flex, msg.flex_len),
            wire=data,
        )

    @staticmethod
    def _decode_obj(o: _WqlObj, cls, errcls: type[Exception]):
        uuid_str = _text(o.uuid, o.uuid_len)
        if uuid_str is None:
            raise errcls("missing required field: uuid")
        world = _text(o.world, o.world_len)
        if world is None:
            raise errcls("missing required field: world_name")
        position = Vector3(o.x, o.y, o.z) if o.has_pos else None
        if cls is Entity and position is None:
            raise errcls("missing required field: position")
        try:
            obj_uuid = uuid_mod.UUID(uuid_str)
        except ValueError as exc:
            raise errcls(f"invalid uuid: {exc}") from exc
        kwargs = dict(
            uuid=obj_uuid,
            world_name=world,
            data=_text(o.data, o.data_len),
            flex=_view(o.flex, o.flex_len),
        )
        if cls is Entity:
            return Entity(position=position, **kwargs)
        return Record(position=position, **kwargs)

    # endregion

    # region: encode

    def encode(self, message: Message) -> bytes:
        if len(message.records) > MAX_OBJS or len(message.entities) > MAX_OBJS:
            raise _TooManyObjects()
        msg = self._scratch
        keep = []  # keep encoded bytes alive across the call

        def blob(value: bytes | None):
            if value is None:
                return None, 0
            keep.append(value)
            return ctypes.cast(ctypes.c_char_p(value), ctypes.c_void_p), len(value)

        msg.instruction = int(message.instruction)
        msg.replication = int(message.replication)
        if message.position is not None:
            msg.has_pos = 1
            msg.x, msg.y, msg.z = (
                message.position.x, message.position.y, message.position.z
            )
        else:
            msg.has_pos = 0
        msg.parameter, msg.parameter_len = blob(
            message.parameter.encode() if message.parameter is not None else None
        )
        msg.sender, msg.sender_len = blob(str(message.sender_uuid).encode())
        msg.world, msg.world_len = blob(message.world_name.encode())
        msg.flex, msg.flex_len = blob(message.flex)
        msg.n_records = len(message.records)
        msg.n_entities = len(message.entities)
        for i, rec in enumerate(message.records):
            self._encode_obj(msg.records[i], rec, blob)
        for i, ent in enumerate(message.entities):
            self._encode_obj(msg.entities[i], ent, blob)

        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        rc = self._lib.wql_encode(
            ctypes.byref(msg), ctypes.byref(out), ctypes.byref(out_len)
        )
        if rc != 0:
            raise RuntimeError(f"native encode failed (rc {rc})")
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.wql_buffer_free(out)

    @staticmethod
    def _encode_obj(slot: _WqlObj, obj, blob) -> None:
        slot.uuid, slot.uuid_len = blob(str(obj.uuid).encode())
        slot.world, slot.world_len = blob(obj.world_name.encode())
        slot.data, slot.data_len = blob(
            obj.data.encode() if obj.data is not None else None
        )
        slot.flex, slot.flex_len = blob(obj.flex)
        if obj.position is not None:
            slot.has_pos = 1
            slot.x, slot.y, slot.z = obj.position.x, obj.position.y, obj.position.z
        else:
            slot.has_pos = 0

    # endregion


class _TooManyObjects(Exception):
    """Internal: exceeds the native object cap; use the Python codec."""


def resolve_lib_path() -> Path | None:
    """Where the native shared library lives, honoring WQL_NATIVE_CODEC
    ('0' disables, '1'/unset = in-tree build, else a path). Shared by
    every native binding (codec, spatial keys) so the policy cannot
    diverge."""
    env = os.environ.get("WQL_NATIVE_CODEC", "1")
    if env == "0":
        return None
    return _LIB_PATH if env == "1" else Path(env)


def load() -> NativeCodec | None:
    """Load the native codec, or None (pure-Python fallback).
    WQL_NATIVE_CODEC: '0' forces the fallback, '1'/unset uses the
    in-tree build, any other value is a path to the shared library
    (containers install it outside the source tree)."""
    env = os.environ.get("WQL_NATIVE_CODEC", "1")
    lib_path = resolve_lib_path()
    if lib_path is None:
        return None
    if not lib_path.exists():
        if env != "1":
            # An explicitly configured path that is missing is a
            # misconfiguration — don't fall back silently.
            logger.warning(
                "WQL_NATIVE_CODEC=%s does not exist; using Python codec",
                env,
            )
        return None
    try:
        codec = NativeCodec(ctypes.CDLL(str(lib_path)))
    except (OSError, AttributeError) as exc:
        # AttributeError: a stale .so missing a symbol — fall back, the
        # server must not die on a leftover build artifact.
        logger.warning("native codec failed to load: %s", exc)
        return None
    # The ctypes struct layout bakes in MAX_OBJS; a library built with a
    # different cap would corrupt memory, so verify instead of trusting.
    lib_cap = codec._lib.wql_max_objs()
    if lib_cap != MAX_OBJS:
        logger.warning(
            "native codec cap mismatch (lib %d != %d) — rebuild "
            "native/libwqlcodec.so; falling back to Python codec",
            lib_cap, MAX_OBJS,
        )
        return None
    return codec
