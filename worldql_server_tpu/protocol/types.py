"""Domain types for the WorldQL wire protocol.

Mirrors the reference's idiomatic layer (worldql_server/src/structures/):
``Message`` is the universal envelope for every instruction
(message.rs:14-24); ``Record``/``Entity`` are positioned payloads
(record.rs:9-15, entity.rs:8-14); ``Vector3`` is an f64 triple
(vector3.rs:11-225).
"""

from __future__ import annotations

import enum
import math
import uuid as uuid_mod
from dataclasses import dataclass, field, replace

NIL_UUID = uuid_mod.UUID(int=0)


class Instruction(enum.IntEnum):
    """14-op instruction set (structures/instruction.rs:7-23).

    Wire values match the FlatBuffers enum exactly
    (WorldQLFB_generated.rs:56-70). Unknown is the catch-all default:
    out-of-range wire values decode to it rather than erroring.
    """

    HEARTBEAT = 0
    HANDSHAKE = 1
    PEER_CONNECT = 2
    PEER_DISCONNECT = 3
    AREA_SUBSCRIBE = 4
    AREA_UNSUBSCRIBE = 5
    GLOBAL_MESSAGE = 6
    LOCAL_MESSAGE = 7
    RECORD_CREATE = 8
    RECORD_READ = 9
    RECORD_UPDATE = 10
    RECORD_DELETE = 11
    RECORD_REPLY = 12
    UNKNOWN = 13

    @classmethod
    def from_wire(cls, value: int) -> "Instruction":
        try:
            return cls(value)
        except ValueError:
            return cls.UNKNOWN


class Replication(enum.IntEnum):
    """Per-message fan-out mode (structures/replication.rs:8-18)."""

    EXCEPT_SELF = 0  # default
    INCLUDING_SELF = 1
    ONLY_SELF = 2

    @classmethod
    def from_wire(cls, value: int) -> "Replication":
        try:
            return cls(value)
        except ValueError:
            return cls.EXCEPT_SELF


@dataclass(frozen=True, slots=True)
class Vector3:
    x: float = 0.0
    y: float = 0.0
    z: float = 0.0

    def __add__(self, other: "Vector3") -> "Vector3":
        return Vector3(self.x + other.x, self.y + other.y, self.z + other.z)

    def __sub__(self, other: "Vector3") -> "Vector3":
        return Vector3(self.x - other.x, self.y - other.y, self.z - other.z)

    def __mul__(self, scalar: float) -> "Vector3":
        return Vector3(self.x * scalar, self.y * scalar, self.z * scalar)

    def __neg__(self) -> "Vector3":
        return Vector3(-self.x, -self.y, -self.z)

    def length(self) -> float:
        return math.sqrt(self.x * self.x + self.y * self.y + self.z * self.z)

    def distance_to(self, other: "Vector3") -> float:
        return (self - other).length()

    def as_tuple(self) -> tuple[float, float, float]:
        return (self.x, self.y, self.z)

    @classmethod
    def zero(cls) -> "Vector3":
        return cls(0.0, 0.0, 0.0)


@dataclass(slots=True)
class Record:
    """Persistent positioned object (structures/record.rs:9-15).

    ``position`` is optional on the wire; records without position are
    accepted by the codec but (like the reference) not yet by the
    region-sharded store paths that require one.
    """

    uuid: uuid_mod.UUID = NIL_UUID
    position: Vector3 | None = None
    world_name: str = ""
    data: str | None = None
    flex: bytes | None = None


@dataclass(slots=True)
class Entity:
    """Live positioned object (structures/entity.rs:8-14); position required."""

    uuid: uuid_mod.UUID = NIL_UUID
    position: Vector3 = field(default_factory=Vector3.zero)
    world_name: str = ""
    data: str | None = None
    flex: bytes | None = None


@dataclass(slots=True)
class Message:
    """The universal wire envelope (structures/message.rs:14-24)."""

    instruction: Instruction = Instruction.UNKNOWN
    parameter: str | None = None
    sender_uuid: uuid_mod.UUID = NIL_UUID
    world_name: str = ""
    replication: Replication = Replication.EXCEPT_SELF
    records: list[Record] = field(default_factory=list)
    entities: list[Entity] = field(default_factory=list)
    position: Vector3 | None = None
    flex: bytes | None = None
    #: inbound wire bytes this Message was decoded from (set by the
    #: decoder; excluded from equality). Fan-out paths that re-broadcast
    #: a message VERBATIM (LocalMessage — the reference re-serializes
    #: the identical struct, message.rs:120-134) reuse these bytes and
    #: skip the encoder entirely. Never set on mutated/constructed
    #: messages; ``with_`` clears it.
    wire: bytes | None = field(default=None, compare=False, repr=False)
    #: cluster trace context ``(trace_id, t_router_ingress_ns)`` set by
    #: a shard's transport after stripping the router's framed prefix
    #: (cluster/tracectx.py); excluded from equality and never
    #: serialized. None everywhere outside a cluster shard — the
    #: single-process paths pay one attribute read at most.
    trace_ctx: tuple | None = field(default=None, compare=False, repr=False)

    def with_(self, **kwargs) -> "Message":
        """Copy with replacements (Rust struct-update syntax analog).
        The copy never inherits ``wire`` — it no longer matches the
        mutated content unless explicitly re-set."""
        kwargs.setdefault("wire", None)
        return replace(self, **kwargs)
