"""Columnar entity wire codec (ctypes binding for the PR 11 natives).

Two GIL-releasing siblings of ``wql_encode_queries`` live in
``native/codec.cpp`` (they need its FlatBuffers reader/writer):

* ``wql_decode_entities`` — batch-decode the ``entities`` lists of a
  whole recv batch straight into preallocated SoA columns (binary uuid
  keys, f32 positions/velocities, per-buffer envelope views). The
  entity vector is read directly off the wire, so this path has NO
  ``WQL_MAX_OBJS`` cap — its only bound is the column capacity, which
  grows pow2 on demand.
* ``wql_encode_entity_frames`` — serialize-once per-cohort neighbor
  frame encoding: N ``entity.frame`` LocalMessages sharing one world
  encode in one native pass, byte-identical to ``wql_encode`` of the
  equivalent ``Message``.

Symbol-probe discipline matches spatial/native_keys.py: each symbol is
probed independently so a stale ``.so`` built before PR 11 degrades
that leg to the object path — same semantics, slower — and never
breaks. ``load()`` returns None when the library itself is absent.

Scratch ownership: ``EntityWire.decode`` returns VIEWS into reusable
scratch columns — valid until the next ``decode`` call. The consumer
(entities/ingest.py) stages them into the plane's own columns in the
same event-loop turn, so nothing outlives the window.
"""

from __future__ import annotations

import ctypes
import logging

import numpy as np

from .native_codec import resolve_lib_path

logger = logging.getLogger(__name__)

_c_i64p = ctypes.POINTER(ctypes.c_int64)
_c_i32p = ctypes.POINTER(ctypes.c_int32)
_c_i8p = ctypes.POINTER(ctypes.c_int8)
_c_u8p = ctypes.POINTER(ctypes.c_uint8)
_c_f32p = ctypes.POINTER(ctypes.c_float)
_c_f64p = ctypes.POINTER(ctypes.c_double)

#: initial entity-column capacity (rows); grows pow2 on demand
_MIN_ROWS = 4096

#: bounded transport recv drain (messages per loop iteration) — the
#: columnar decode amortizes across it; past this the loop yields
RECV_DRAIN_MAX = 256

WQL_E_CAPACITY = -4


class DecodedBatch:
    """One recv batch's columnar decode. Arrays are views into the
    decoder's scratch — consume before the next ``decode`` call."""

    __slots__ = (
        "status", "instr", "sender_keys", "world_off", "world_len",
        "ent_start", "ent_count", "uuid_keys", "pos", "vel", "has_vel",
        "total",
    )

    def __init__(self, status, instr, sender_keys, world_off, world_len,
                 ent_start, ent_count, uuid_keys, pos, vel, has_vel,
                 total):
        self.status = status
        self.instr = instr
        self.sender_keys = sender_keys
        self.world_off = world_off
        self.world_len = world_len
        self.ent_start = ent_start
        self.ent_count = ent_count
        self.uuid_keys = uuid_keys
        self.pos = pos
        self.vel = vel
        self.has_vel = has_vel
        self.total = total


class EntityWire:
    """Bound native entity codec. ``can_decode``/``can_encode_frames``
    reflect which symbols this build of the library actually has."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self._decode = getattr(lib, "wql_decode_entities", None)
        if self._decode is not None:
            self._decode.restype = ctypes.c_int64
            self._decode.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), _c_i64p, ctypes.c_int64,
                _c_i8p, _c_u8p, _c_u8p, _c_i64p, _c_i32p, _c_i64p,
                _c_i32p, ctypes.c_int64, _c_u8p, _c_f32p, _c_f32p,
                _c_u8p,
            ]
        self._encode_frames = getattr(lib, "wql_encode_entity_frames", None)
        if self._encode_frames is not None:
            self._encode_frames.restype = ctypes.c_int
            self._encode_frames.argtypes = [
                _c_u8p, _c_u8p, _c_f64p, ctypes.c_int64,
                ctypes.c_char_p, ctypes.c_int32,
                ctypes.POINTER(_c_u8p), _c_i64p, _c_i64p,
            ]
        self._encode_interest = getattr(
            lib, "wql_encode_interest_frame", None
        )
        if self._encode_interest is not None:
            self._encode_interest.restype = ctypes.c_int
            self._encode_interest.argtypes = [
                ctypes.c_char_p, ctypes.c_int32,
                ctypes.c_char_p, ctypes.c_int32,
                _c_u8p, _c_f64p, _c_u8p, ctypes.c_int64,
                ctypes.POINTER(_c_u8p), _c_i64p,
            ]
        self._free = lib.wql_buffer_free
        self._free.argtypes = [_c_u8p]
        self._free.restype = None
        # reusable entity-column scratch (pow2 rows)
        self._rows = _MIN_ROWS
        self._alloc_columns()

    def _alloc_columns(self) -> None:
        rows = self._rows
        self._uuid_keys = np.empty((rows, 16), np.uint8)
        self._pos = np.empty((rows, 3), np.float32)
        self._vel = np.empty((rows, 3), np.float32)
        self._has_vel = np.empty(rows, np.uint8)

    @property
    def can_decode(self) -> bool:
        return self._decode is not None

    @property
    def can_encode_frames(self) -> bool:
        return self._encode_frames is not None

    @property
    def can_encode_interest(self) -> bool:
        return self._encode_interest is not None

    # region: decode

    def decode(self, datas: list[bytes]) -> DecodedBatch:
        """Batch-decode a recv batch into columns (one GIL-releasing
        native call; retries with doubled columns on capacity)."""
        n = len(datas)
        bufs = (ctypes.c_char_p * n)(*datas)
        lens = np.fromiter(map(len, datas), np.int64, count=n)
        status = np.empty(n, np.int8)
        instr = np.empty(n, np.uint8)
        sender_keys = np.empty((n, 16), np.uint8)
        world_off = np.empty(n, np.int64)
        world_len = np.empty(n, np.int32)
        ent_start = np.empty(n, np.int64)
        ent_count = np.empty(n, np.int32)
        while True:
            total = self._decode(
                bufs,
                lens.ctypes.data_as(_c_i64p),
                n,
                status.ctypes.data_as(_c_i8p),
                instr.ctypes.data_as(_c_u8p),
                sender_keys.ctypes.data_as(_c_u8p),
                world_off.ctypes.data_as(_c_i64p),
                world_len.ctypes.data_as(_c_i32p),
                ent_start.ctypes.data_as(_c_i64p),
                ent_count.ctypes.data_as(_c_i32p),
                self._rows,
                self._uuid_keys.ctypes.data_as(_c_u8p),
                self._pos.ctypes.data_as(_c_f32p),
                self._vel.ctypes.data_as(_c_f32p),
                self._has_vel.ctypes.data_as(_c_u8p),
            )
            if total != WQL_E_CAPACITY:
                break
            self._rows *= 2
            self._alloc_columns()
        return DecodedBatch(
            status, instr, sender_keys, world_off, world_len, ent_start,
            ent_count, self._uuid_keys, self._pos, self._vel,
            self._has_vel, int(total),
        )

    # endregion

    # region: frame encode

    def encode_frames(self, sender_keys: np.ndarray,
                      ent_keys: np.ndarray, pos: np.ndarray,
                      world: bytes) -> list[bytes]:
        """Encode one cohort's neighbor frames in a single native pass:
        ``[n,16]u8`` sender/entity uuid keys + ``[n,3]f64`` positions +
        one shared world → per-frame wire bytes."""
        n = len(ent_keys)
        sk = np.ascontiguousarray(sender_keys, np.uint8)
        ek = np.ascontiguousarray(ent_keys, np.uint8)
        p = np.ascontiguousarray(pos, np.float64)
        off = np.empty(n, np.int64)
        lens = np.empty(n, np.int64)
        out = _c_u8p()
        rc = self._encode_frames(
            sk.ctypes.data_as(_c_u8p),
            ek.ctypes.data_as(_c_u8p),
            p.ctypes.data_as(_c_f64p),
            n, world, len(world),
            ctypes.byref(out),
            off.ctypes.data_as(_c_i64p),
            lens.ctypes.data_as(_c_i64p),
        )
        if rc != 0:
            raise RuntimeError(f"native frame encode failed (rc {rc})")
        try:
            blob = ctypes.string_at(out, int(off[-1] + lens[-1])) if n else b""
        finally:
            self._free(out)
        return [
            blob[o:o + ln]
            for o, ln in zip(off.tolist(), lens.tolist())
        ]

    def encode_interest_frame(self, param: bytes, world: bytes,
                              ent_keys: np.ndarray, pos: np.ndarray,
                              tomb: np.ndarray) -> bytes:
        """Encode ONE interest-managed frame (ISSUE 18) natively:
        stamped parameter + shared world + ``[n,16]u8`` entity keys +
        ``[n,3]f64`` positions + ``[n]u8`` tombstone flags → wire
        bytes, byte-identical to ``serialize_message`` of the
        equivalent Message (the cohort template the manager patches
        per peer)."""
        n = len(ent_keys)
        ek = np.ascontiguousarray(ent_keys, np.uint8)
        p = np.ascontiguousarray(pos, np.float64)
        tb = np.ascontiguousarray(tomb, np.uint8)
        out = _c_u8p()
        out_len = ctypes.c_int64()
        rc = self._encode_interest(
            param, len(param), world, len(world),
            ek.ctypes.data_as(_c_u8p),
            p.ctypes.data_as(_c_f64p),
            tb.ctypes.data_as(_c_u8p),
            n,
            ctypes.byref(out),
            ctypes.byref(out_len),
        )
        if rc != 0:
            raise RuntimeError(f"native interest encode failed (rc {rc})")
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._free(out)

    # endregion


_shared: EntityWire | None = None
_shared_loaded = False


def shared() -> EntityWire | None:
    """Process-wide lazily-loaded instance (one CDLL + one scratch set
    per process; callers on the event loop share it safely)."""
    global _shared, _shared_loaded
    if not _shared_loaded:
        _shared = load()
        _shared_loaded = True
    return _shared


def load() -> EntityWire | None:
    """Load the native entity codec, or None (object-path fallback).
    Honors WQL_NATIVE_CODEC exactly like the message codec."""
    lib_path = resolve_lib_path()
    if lib_path is None or not lib_path.exists():
        return None
    try:
        lib = ctypes.CDLL(str(lib_path))
        abi = getattr(lib, "wql_entities_abi", None)
        if abi is None:
            # stale .so from before PR 11 — the object path still works
            logger.warning(
                "native library has no entity codec (stale build) — "
                "entity ingest stays on the object path"
            )
            return None
        abi.restype = ctypes.c_int64
        abi.argtypes = []
        if abi() != 1:
            logger.warning("native entity codec ABI mismatch — object path")
            return None
        return EntityWire(lib)
    except (OSError, AttributeError) as exc:
        logger.warning("native entity codec unavailable: %s", exc)
        return None
