"""FlatBuffers wire codec for the WorldQL ``Message`` envelope.

Wire-compatible with the reference's generated codec
(worldql_server/src/flatbuffers/WorldQLFB_generated.rs; schema
reconstructed in ``worldql.fbs``). Buffers are finished without a file
identifier or size prefix (structures/message.rs:120-134).

Unlike the reference — which funnels every serialization through one
global ``Lazy<Mutex<FlatBufferBuilder>>`` (message.rs:116-117, a
deliberate single-builder bottleneck) — serialization here is
re-entrant: each call uses its own builder, so per-peer sends can
serialize concurrently.

The Python FlatBuffers runtime has no verifier; the reader below is
pure Python with bounds-checked slicing, so malformed buffers raise
``DeserializeError`` rather than reading out of bounds. Transports
additionally cap frame size.
"""

from __future__ import annotations

import uuid as uuid_mod

import flatbuffers
from flatbuffers import encode as fb_encode
from flatbuffers import number_types as N
from flatbuffers.table import Table

from .types import Entity, Instruction, Message, Record, Replication, Vector3

# Message table vtable slots (WorldQLFB_generated.rs:939-947)
_MSG_INSTRUCTION = 0
_MSG_PARAMETER = 1
_MSG_SENDER_UUID = 2
_MSG_WORLD_NAME = 3
_MSG_REPLICATION = 4
_MSG_RECORDS = 5
_MSG_ENTITIES = 6
_MSG_POSITION = 7
_MSG_FLEX = 8

# Record/Entity table vtable slots (WorldQLFB_generated.rs:485-489)
_OBJ_UUID = 0
_OBJ_POSITION = 1
_OBJ_WORLD_NAME = 2
_OBJ_DATA = 3
_OBJ_FLEX = 4


class DeserializeError(ValueError):
    """Invalid flatbuffer or missing required fields
    (message.rs:145-152)."""


# region: writing


def _create_vec3d(builder: flatbuffers.Builder, v: Vector3) -> int:
    """Write the 24-byte Vec3d struct inline (x, y, z f64)."""
    builder.Prep(8, 24)
    builder.PrependFloat64(v.z)
    builder.PrependFloat64(v.y)
    builder.PrependFloat64(v.x)
    return builder.Offset()


def _write_obj(builder: flatbuffers.Builder, obj: Record | Entity) -> int:
    """Write one Record/Entity table; both share the same layout."""
    uuid_off = builder.CreateString(str(obj.uuid))
    world_off = builder.CreateString(obj.world_name)
    data_off = builder.CreateString(obj.data) if obj.data is not None else None
    flex_off = builder.CreateByteVector(obj.flex) if obj.flex is not None else None

    builder.StartObject(5)
    builder.PrependUOffsetTRelativeSlot(_OBJ_UUID, uuid_off, 0)
    if obj.position is not None:
        pos_off = _create_vec3d(builder, obj.position)
        builder.PrependStructSlot(_OBJ_POSITION, pos_off, 0)
    builder.PrependUOffsetTRelativeSlot(_OBJ_WORLD_NAME, world_off, 0)
    if data_off is not None:
        builder.PrependUOffsetTRelativeSlot(_OBJ_DATA, data_off, 0)
    if flex_off is not None:
        builder.PrependUOffsetTRelativeSlot(_OBJ_FLEX, flex_off, 0)
    return builder.EndObject()


def _write_obj_vector(builder: flatbuffers.Builder, offsets: list[int]) -> int:
    builder.StartVector(4, len(offsets), 4)
    for off in reversed(offsets):
        builder.PrependUOffsetTRelative(off)
    return builder.EndVector()


def serialize_message(message: Message) -> bytes:
    """Message → wire bytes. Always writes sender_uuid and world_name,
    like the reference encoder (message.rs:41-52)."""
    builder = flatbuffers.Builder(256)

    record_offs = [_write_obj(builder, r) for r in message.records]
    entity_offs = [_write_obj(builder, e) for e in message.entities]

    records_vec = _write_obj_vector(builder, record_offs) if record_offs else None
    entities_vec = _write_obj_vector(builder, entity_offs) if entity_offs else None

    param_off = (
        builder.CreateString(message.parameter)
        if message.parameter is not None
        else None
    )
    sender_off = builder.CreateString(str(message.sender_uuid))
    world_off = builder.CreateString(message.world_name)
    flex_off = (
        builder.CreateByteVector(message.flex) if message.flex is not None else None
    )

    builder.StartObject(9)
    builder.PrependUint8Slot(_MSG_INSTRUCTION, int(message.instruction), 0)
    if param_off is not None:
        builder.PrependUOffsetTRelativeSlot(_MSG_PARAMETER, param_off, 0)
    builder.PrependUOffsetTRelativeSlot(_MSG_SENDER_UUID, sender_off, 0)
    builder.PrependUOffsetTRelativeSlot(_MSG_WORLD_NAME, world_off, 0)
    builder.PrependUint8Slot(_MSG_REPLICATION, int(message.replication), 0)
    if records_vec is not None:
        builder.PrependUOffsetTRelativeSlot(_MSG_RECORDS, records_vec, 0)
    if entities_vec is not None:
        builder.PrependUOffsetTRelativeSlot(_MSG_ENTITIES, entities_vec, 0)
    if message.position is not None:
        pos_off = _create_vec3d(builder, message.position)
        builder.PrependStructSlot(_MSG_POSITION, pos_off, 0)
    if flex_off is not None:
        builder.PrependUOffsetTRelativeSlot(_MSG_FLEX, flex_off, 0)
    root = builder.EndObject()

    builder.Finish(root)
    return bytes(builder.Output())


# endregion

# region: reading


def _slot(table: Table, slot: int) -> int:
    """Field offset for vtable slot N, or 0 if absent."""
    return table.Offset(4 + 2 * slot)


def _read_string(table: Table, slot: int) -> str | None:
    o = _slot(table, slot)
    if o == 0:
        return None
    raw = table.String(o + table.Pos)
    return raw.decode("utf-8")


def _read_bytes(table: Table, slot: int) -> bytes | None:
    o = _slot(table, slot)
    if o == 0:
        return None
    start = table.Vector(o)
    length = table.VectorLen(o)
    return bytes(table.Bytes[start : start + length])


def _read_u8(table: Table, slot: int, default: int) -> int:
    o = _slot(table, slot)
    if o == 0:
        return default
    return table.Get(N.Uint8Flags, o + table.Pos)


def _read_vec3d(table: Table, slot: int) -> Vector3 | None:
    o = _slot(table, slot)
    if o == 0:
        return None
    base = o + table.Pos
    return Vector3(
        table.Get(N.Float64Flags, base),
        table.Get(N.Float64Flags, base + 8),
        table.Get(N.Float64Flags, base + 16),
    )


def _read_obj(table: Table, cls: type) -> Record | Entity:
    uuid_str = _read_string(table, _OBJ_UUID)
    if uuid_str is None:
        raise DeserializeError("missing required field: uuid")
    position = _read_vec3d(table, _OBJ_POSITION)
    world_name = _read_string(table, _OBJ_WORLD_NAME)
    if world_name is None:
        raise DeserializeError("missing required field: world_name")

    if cls is Entity and position is None:
        raise DeserializeError("missing required field: position")

    return cls(
        uuid=uuid_mod.UUID(uuid_str),
        position=position,
        world_name=world_name,
        data=_read_string(table, _OBJ_DATA),
        flex=_read_bytes(table, _OBJ_FLEX),
    )


def _read_obj_vector(table: Table, slot: int, cls: type) -> list:
    o = _slot(table, slot)
    if o == 0:
        return []
    length = table.VectorLen(o)
    out = []
    for i in range(length):
        x = table.Vector(o) + i * 4
        x = table.Indirect(x)
        out.append(_read_obj(Table(table.Bytes, x), cls))
    return out


def deserialize_message(buf: bytes | bytearray | memoryview) -> Message:
    """Wire bytes → Message.

    Required-field semantics match the reference decoder
    (message.rs:56-111): world_name and sender_uuid must be present and
    the uuid must parse; unknown instruction values map to
    ``Instruction.UNKNOWN``; unknown replication values map to the
    default ``EXCEPT_SELF``.
    """
    try:
        # Snapshot mutable receive buffers FIRST: ``Message.wire`` is
        # the serialize-once broadcast cache, shared and concatenated
        # into frames that outlive this call — a reused bytearray would
        # corrupt re-broadcasts and a memoryview breaks frame concat
        # (ADVICE r5). ``bytes(bytes)`` is a no-copy identity.
        buf = bytes(buf)
        if len(buf) < 8:
            raise DeserializeError("buffer too small")
        root = fb_encode.Get(N.UOffsetTFlags.packer_type, buf, 0)
        if root + 4 > len(buf):
            raise DeserializeError("root offset out of bounds")
        table = Table(buf, root)

        sender_str = _read_string(table, _MSG_SENDER_UUID)
        if sender_str is None:
            raise DeserializeError("missing required field: sender_uuid")
        world_name = _read_string(table, _MSG_WORLD_NAME)
        if world_name is None:
            raise DeserializeError("missing required field: world_name")

        return Message(
            instruction=Instruction.from_wire(
                _read_u8(table, _MSG_INSTRUCTION, 0)
            ),
            parameter=_read_string(table, _MSG_PARAMETER),
            sender_uuid=uuid_mod.UUID(sender_str),
            world_name=world_name,
            replication=Replication.from_wire(
                _read_u8(table, _MSG_REPLICATION, 0)
            ),
            records=_read_obj_vector(table, _MSG_RECORDS, Record),
            entities=_read_obj_vector(table, _MSG_ENTITIES, Entity),
            position=_read_vec3d(table, _MSG_POSITION),
            flex=_read_bytes(table, _MSG_FLEX),
            wire=buf,
        )
    except DeserializeError:
        raise
    except Exception as exc:  # malformed buffer → typed error, never OOB
        raise DeserializeError(f"invalid flatbuffer: {exc}") from exc


# endregion

# region: native dispatch

# Pure-Python implementations stay importable for tests and fallback.
py_serialize_message = serialize_message
py_deserialize_message = deserialize_message

from . import native_codec as _native_codec  # noqa: E402

_native = _native_codec.load()

#: codec health counters, exported as the `codec` gauge by the server.
#: obj_overflow: messages whose records/entities exceeded WQL_MAX_OBJS
#: and silently took the ~10x-slower Python codec — before this counter
#: that cliff was invisible (ISSUE 11 satellite). Plain int increments:
#: the codec runs on the event loop and in sender workers, each process
#: counting its own.
codec_stats = {"obj_overflow": 0}

if _native is not None:

    def serialize_message(message: Message) -> bytes:  # noqa: F811
        try:
            return _native.encode(message)
        except _native_codec._TooManyObjects:
            codec_stats["obj_overflow"] += 1
            return py_serialize_message(message)

    def deserialize_message(buf: bytes | bytearray | memoryview) -> Message:  # noqa: F811
        try:
            return _native.decode(bytes(buf), DeserializeError)
        except _native_codec._TooManyObjects:
            codec_stats["obj_overflow"] += 1
            return py_deserialize_message(bytes(buf))

# endregion
