from .codec import DeserializeError, deserialize_message, serialize_message
from .types import (
    NIL_UUID,
    Entity,
    Instruction,
    Message,
    Record,
    Replication,
    Vector3,
)

__all__ = [
    "NIL_UUID",
    "Entity",
    "Instruction",
    "Message",
    "Record",
    "Replication",
    "Vector3",
    "DeserializeError",
    "deserialize_message",
    "serialize_message",
]
