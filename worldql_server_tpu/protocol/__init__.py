from .codec import (
    DeserializeError,
    codec_stats,
    deserialize_message,
    serialize_message,
)
from .types import (
    NIL_UUID,
    Entity,
    Instruction,
    Message,
    Record,
    Replication,
    Vector3,
)

__all__ = [
    "NIL_UUID",
    "Entity",
    "Instruction",
    "Message",
    "Record",
    "Replication",
    "Vector3",
    "DeserializeError",
    "codec_stats",
    "deserialize_message",
    "serialize_message",
]
