"""HTTP REST ingest transport.

Rebuild of the reference's axum server
(worldql_server/src/transport/http/http_rest.rs): a single route
``POST /global_message`` taking JSON ``{parameter?, world_name}``,
injected as a GlobalMessage with nil sender and ExceptSelf replication
(http_rest.rs:40-60). Optional static bearer-token auth
(http_rest.rs:85-98); success replies 204 No Content (http_rest.rs:104).
HTTP callers are never peers — this is a fire-and-forget
server→clients bridge (e.g. webhooks).
"""

from __future__ import annotations

import logging

from aiohttp import web

from ..protocol import Instruction, Message, Replication
from ..protocol.types import NIL_UUID

logger = logging.getLogger(__name__)


class HttpTransport:
    def __init__(self, server):
        self.server = server
        self._runner: web.AppRunner | None = None

    async def start(self) -> None:
        config = self.server.config
        app = web.Application()
        app.router.add_post("/global_message", self._post_global_message)
        # Observability beyond the reference (SURVEY §5: it has neither
        # a health endpoint nor metrics).
        app.router.add_get("/healthz", self._get_healthz)
        app.router.add_get("/metrics", self._get_metrics)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, config.http_host, config.http_port)
        await site.start()
        logger.info(
            "HTTP server listening on %s:%s", config.http_host, config.http_port
        )

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    def _authorized(self, request: web.Request) -> bool:
        token = self.server.config.http_auth_token
        if token is None:
            return True
        auth = request.headers.get("Authorization", "")
        return auth.startswith("Bearer ") and auth[len("Bearer "):] == token

    async def _get_healthz(self, request: web.Request) -> web.Response:
        body = {"status": "ok"}
        # Durability state rides health (queue depth, WAL segments,
        # last recovery) — an operator probing a draining/replaying
        # node needs this before scraping full metrics. Omitted when
        # durability is off so the reference-equivalent body stays
        # byte-for-byte identical.
        status_fn = getattr(self.server, "durability_status", None)
        status = status_fn() if status_fn is not None else None
        if status is not None:
            body["durability"] = status
        return web.json_response(body)

    async def _get_metrics(self, request: web.Request) -> web.Response:
        if not self._authorized(request):
            return web.Response(status=401)
        # Content negotiation: callers that ask for JSON (dashboards,
        # the test suite) get the structured snapshot; everything else
        # — Prometheus scrapers send Accept: text/plain /
        # openmetrics-text — gets the standard exposition format.
        if "application/json" in request.headers.get("Accept", ""):
            return web.json_response(self.server.metrics.snapshot())
        return web.Response(
            text=self.server.metrics.render_prometheus(),
            content_type="text/plain",
            charset="utf-8",
        )

    async def _post_global_message(self, request: web.Request) -> web.Response:
        if not self._authorized(request):
            return web.Response(status=401)

        try:
            body = await request.json()
            world_name = body["world_name"]
            parameter = body.get("parameter")
            if not isinstance(world_name, str) or not (
                parameter is None or isinstance(parameter, str)
            ):
                raise ValueError("wrong field types")
        except Exception:
            return web.Response(status=400)

        message = Message(
            instruction=Instruction.GLOBAL_MESSAGE,
            parameter=parameter,
            sender_uuid=NIL_UUID,
            world_name=world_name,
            replication=Replication.EXCEPT_SELF,
        )
        await self.server.router.handle_message(message)
        return web.Response(status=204)
