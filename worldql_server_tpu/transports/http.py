"""HTTP REST ingest transport.

Rebuild of the reference's axum server
(worldql_server/src/transport/http/http_rest.rs): a single route
``POST /global_message`` taking JSON ``{parameter?, world_name}``,
injected as a GlobalMessage with nil sender and ExceptSelf replication
(http_rest.rs:40-60). Optional static bearer-token auth
(http_rest.rs:85-98); success replies 204 No Content (http_rest.rs:104).
HTTP callers are never peers — this is a fire-and-forget
server→clients bridge (e.g. webhooks).
"""

from __future__ import annotations

import logging

from aiohttp import web

from ..protocol import Instruction, Message, Replication
from ..protocol.types import NIL_UUID
from ..robustness import failpoints

logger = logging.getLogger(__name__)


class HttpTransport:
    def __init__(self, server):
        self.server = server
        self._runner: web.AppRunner | None = None

    async def start(self) -> None:
        config = self.server.config
        app = web.Application()
        app.router.add_post("/global_message", self._post_global_message)
        # Observability beyond the reference (SURVEY §5: it has neither
        # a health endpoint nor metrics).
        app.router.add_get("/healthz", self._get_healthz)
        app.router.add_get("/metrics", self._get_metrics)
        if config.failpoints_admin:
            # fault-injection toggle — an explicit operator opt-in
            # (WQL_FAILPOINTS_ADMIN=1 / --failpoints-admin); absent
            # otherwise, so the route 404s like any unknown path
            app.router.add_get("/failpoints", self._get_failpoints)
            app.router.add_post("/failpoints", self._post_failpoints)
        if getattr(self.server, "heatmap", None) is not None:
            # region-density heatmap feed (queries/heatmap.py) — exists
            # only with the query library on, 404s otherwise
            app.router.add_get("/debug/heatmap", self._get_debug_heatmap)
        if getattr(self.server, "recorder", None) is not None:
            # flight recorder debug surface — exists only when tracing
            # is on (--trace / --slow-tick-ms), 404s otherwise
            app.router.add_get("/debug/ticks", self._get_debug_ticks)
            app.router.add_post("/debug/profile", self._post_debug_profile)
            app.router.add_get("/debug/profile", self._get_debug_profile)
        if getattr(self.server, "slo", None) is not None:
            # SLO burn-state report — exists only with --slo on /
            # --slo-file, 404s otherwise
            app.router.add_get("/debug/slo", self._get_debug_slo)
        if getattr(self.server, "incidents", None) is not None:
            # incident capsule ring — exists only with --incident-dir
            app.router.add_get("/debug/incidents", self._get_debug_incidents)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, config.http_host, config.http_port)
        await site.start()
        logger.info(
            "HTTP server listening on %s:%s", config.http_host, config.http_port
        )

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    def _authorized(self, request: web.Request) -> bool:
        token = self.server.config.http_auth_token
        if token is None:
            return True
        auth = request.headers.get("Authorization", "")
        return auth.startswith("Bearer ") and auth[len("Bearer "):] == token

    async def _get_healthz(self, request: web.Request) -> web.Response:
        body = {"status": "ok"}
        # Durability state rides health (queue depth, WAL segments,
        # last recovery) — an operator probing a draining/replaying
        # node needs this before scraping full metrics. Omitted when
        # durability is off so the reference-equivalent body stays
        # byte-for-byte identical.
        status_fn = getattr(self.server, "durability_status", None)
        status = status_fn() if status_fn is not None else None
        if status is not None:
            body["durability"] = status
        # Supervision state: per-task health plus the tasks_unhealthy
        # gauge. Only present once something is actually supervised,
        # so minimal servers keep the reference-shaped body.
        supervisor = getattr(self.server, "supervisor", None)
        if supervisor is not None and supervisor.task_count():
            stats = supervisor.stats()
            body["tasks_unhealthy"] = stats["tasks_unhealthy"]
            body["supervisor"] = stats
            if stats["tasks_unhealthy"]:
                body["status"] = "degraded"
        # Degraded-mode spatial backend (ResilientBackend): failover is
        # THE signal an orchestrator restarts a node on.
        res_fn = getattr(self.server, "resilience_status", None)
        resilience = res_fn() if res_fn is not None else None
        if resilience is not None:
            body["resilience"] = resilience
            if resilience["degraded"]:
                body["status"] = "degraded"
        # Delivery-plane state (worker liveness + drop counters): a
        # retired or dead sender worker is a capacity loss the
        # orchestrator should see without scraping /metrics. Absent
        # with --delivery-workers 0 (reference-shaped body).
        dlv_fn = getattr(self.server, "delivery_status", None)
        delivery = dlv_fn() if dlv_fn is not None else None
        if delivery is not None:
            body["delivery"] = delivery
            if delivery["degraded"]:
                body["status"] = "degraded"
        # Session continuity (parked/resumed/expired accounting): a
        # reconnect storm's progress — how many peers are parked and
        # how fast resumes are landing — is the first thing an
        # operator needs mid-blip. Absent with --session-ttl 0
        # (reference-shaped body).
        ses_fn = getattr(self.server, "sessions_status", None)
        sessions = ses_fn() if ses_fn is not None else None
        if sessions is not None:
            body["sessions"] = sessions
        # Overload governor (admission state + shed accounting): an
        # orchestrator deciding whether to scale out needs the
        # governor's state before anything else. SHED_HIGH/REJECT
        # report degraded — the node is up but refusing work. Absent
        # with --overload off (reference-shaped body).
        ovl_fn = getattr(self.server, "overload_status", None)
        overload = ovl_fn() if ovl_fn is not None else None
        if overload is not None:
            body["overload"] = overload
            if overload["state_level"] >= 2:
                body["status"] = "degraded"
        # SLO burn state (worst objective + who is burning): BURNING
        # means the node is violating a declared objective RIGHT NOW —
        # degraded, even though it is serving. Absent with --slo off
        # (reference-shaped body).
        slo_fn = getattr(self.server, "slo_status", None)
        slo = slo_fn() if slo_fn is not None else None
        if slo is not None:
            body["slo"] = slo
            if slo["burning"]:
                body["status"] = "degraded"
        # Flight-recorder state (slow-tick count front and center): an
        # operator probing a limping node sees HOW MANY ticks blew the
        # threshold before scraping anything. Absent when tracing is
        # off so the minimal body stays reference-shaped.
        recorder = getattr(self.server, "recorder", None)
        if recorder is not None:
            body["flight_recorder"] = recorder.stats()
        return web.json_response(body)

    async def _get_debug_ticks(self, request: web.Request) -> web.Response:
        """Flight-recorder dump: the last N tick traces (plus the loose
        message/WAL spans). ``?format=chrome`` renders Trace Event
        Format JSON loadable in chrome://tracing / ui.perfetto.dev."""
        if not self._authorized(request):
            return web.Response(status=401)
        recorder = self.server.recorder
        ticks = recorder.snapshot()
        if request.query.get("format") == "chrome":
            from ..observability.export import chrome_trace

            # named pid lane (satellite of ISSUE 15): a shard's dump
            # says which shard it is, a standalone server says so too
            cluster = getattr(self.server, "cluster", None)
            process_name = (
                f"shard-{cluster.shard_id}" if cluster is not None
                else "worldql-server"
            )
            return web.json_response(
                chrome_trace(
                    ticks + recorder.loose_snapshot(),
                    process_name=process_name,
                )
            )
        return web.json_response({
            "recorder": recorder.stats(),
            "ticks": ticks,
            "loose": recorder.loose_snapshot(),
        })

    async def _get_debug_slo(self, request: web.Request) -> web.Response:
        """Full SLO report: per-objective state, fast/slow burn rates,
        budget-remaining, transition counts, and (on a router) every
        shard's piggybacked compliance summary."""
        if not self._authorized(request):
            return web.Response(status=401)
        return web.json_response(self.server.slo.status())

    async def _get_debug_incidents(self, request: web.Request) -> web.Response:
        """Incident capsule ring: no query = the index (id, seq,
        objective, size); ``?id=incident-NNNN-<objective>`` = the full
        capsule JSON."""
        if not self._authorized(request):
            return web.Response(status=401)
        incidents = self.server.incidents
        incident_id = request.query.get("id")
        if incident_id is None:
            return web.json_response({
                "incidents": incidents.list(),
                "stats": incidents.stats(),
            })
        capsule = incidents.load(incident_id)
        if capsule is None:
            return web.Response(status=404)
        return web.json_response(capsule)

    async def _get_debug_heatmap(self, request: web.Request) -> web.Response:
        """Region-density snapshot: the decayed per-cube counts feeding
        the ``wql_region_density`` gauge, grouped by world — the raw
        heatmap a dashboard tiles. ``?n=`` caps the per-world rows."""
        if not self._authorized(request):
            return web.Response(status=401)
        try:
            n = int(request.query.get("n", 0)) or None
        except ValueError:
            return web.Response(status=400)
        return web.json_response(self.server.heatmap.snapshot(n=n))

    async def _get_debug_profile(self, request: web.Request) -> web.Response:
        if not self._authorized(request):
            return web.Response(status=401)
        return web.json_response(self.server.profiler.status())

    async def _post_debug_profile(self, request: web.Request) -> web.Response:
        """Device-level escalation: JSON ``{"action": "start", "dir":
        PATH}`` begins a jax.profiler capture, ``{"action": "stop"}``
        ends it (trace lands in the start dir, viewable with xprof/
        tensorboard)."""
        if not self._authorized(request):
            return web.Response(status=401)
        try:
            body = await request.json()
            action = body.get("action")
        except Exception:
            return web.Response(status=400)
        profiler = self.server.profiler
        try:
            if action == "start":
                log_dir = body.get("dir")
                if not isinstance(log_dir, str) or not log_dir:
                    return web.json_response(
                        {"error": "start requires a 'dir' string"},
                        status=400,
                    )
                profiler.start(log_dir)
            elif action == "stop":
                profiler.stop()
            else:
                return web.json_response(
                    {"error": "action must be 'start' or 'stop'"},
                    status=400,
                )
        except RuntimeError as exc:  # double start / stop without start
            return web.json_response({"error": str(exc)}, status=409)
        except Exception as exc:  # jax missing / profiler backend error
            logger.exception("jax profiler hook failed")
            return web.json_response({"error": str(exc)}, status=500)
        return web.json_response(profiler.status())

    async def _get_failpoints(self, request: web.Request) -> web.Response:
        if not self._authorized(request):
            return web.Response(status=401)
        return web.json_response({
            "active": failpoints.registry.active(),
            "points": failpoints.registry.stats(),
        })

    async def _post_failpoints(self, request: web.Request) -> web.Response:
        """Replace the armed failpoint set: JSON ``{"spec": "...",
        "seed": N?}`` or a raw text spec body. An empty spec disarms
        everything."""
        if not self._authorized(request):
            return web.Response(status=401)
        try:
            if "application/json" in request.headers.get("Content-Type", ""):
                body = await request.json()
                spec = body.get("spec", "")
                seed = body.get("seed")
            else:
                spec = (await request.text()).strip()
                seed = None
            if not isinstance(spec, str) or not (
                seed is None or isinstance(seed, int)
            ):
                raise ValueError("wrong field types")
            failpoints.registry.configure(spec, seed=seed)
        except failpoints.FailpointSpecError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        except Exception:
            return web.Response(status=400)
        return web.json_response({
            "active": failpoints.registry.active(),
            "points": failpoints.registry.stats(),
        })

    async def _get_metrics(self, request: web.Request) -> web.Response:
        if not self._authorized(request):
            return web.Response(status=401)
        # Content negotiation: callers that ask for JSON (dashboards,
        # the test suite) get the structured snapshot; everything else
        # — Prometheus scrapers send Accept: text/plain /
        # openmetrics-text — gets the standard exposition format.
        if "application/json" in request.headers.get("Accept", ""):
            return web.json_response(self.server.metrics.snapshot())
        return web.Response(
            text=self.server.metrics.render_prometheus(),
            content_type="text/plain",
            charset="utf-8",
        )

    async def _post_global_message(self, request: web.Request) -> web.Response:
        if not self._authorized(request):
            return web.Response(status=401)

        try:
            body = await request.json()
            world_name = body["world_name"]
            parameter = body.get("parameter")
            if not isinstance(world_name, str) or not (
                parameter is None or isinstance(parameter, str)
            ):
                raise ValueError("wrong field types")
        except Exception:
            return web.Response(status=400)

        message = Message(
            instruction=Instruction.GLOBAL_MESSAGE,
            parameter=parameter,
            sender_uuid=NIL_UUID,
            world_name=world_name,
            replication=Replication.EXCEPT_SELF,
        )
        await self.server.router.handle_message(message)
        return web.Response(status=204)
