"""Server→client WebSocket frame assembly — dependency-free.

Split out of ``transports/websocket.py`` so the delivery-plane sender
workers (worldql_server_tpu/delivery/worker.py) can frame WS payloads
without importing the ``websockets`` library (absent in minimal
containers) or any of the parent's asyncio transport machinery.
"""

from __future__ import annotations

import struct


def ws_binary_frame(payload: bytes) -> bytes:
    """A complete server→client binary frame (FIN, unmasked — RFC 6455
    §5.2; servers MUST NOT mask). Identical bytes for every recipient,
    which is what lets a broadcast frame once for all targets."""
    n = len(payload)
    if n < 126:
        return struct.pack(">BB", 0x82, n) + payload
    if n < 1 << 16:
        return struct.pack(">BBH", 0x82, 126, n) + payload
    return struct.pack(">BBQ", 0x82, 127, n) + payload
