"""WebSocket transport.

Rebuild of the reference's WS server
(worldql_server/src/transport/http/websocket.rs): the *server* assigns
the peer UUID (contrast ZeroMQ, where the client picks), sends a
client-bound Handshake carrying that UUID as ``parameter``, and
requires the client's first frame to be a Handshake echo with the
assigned UUID as sender. After that, every binary frame must
deserialize and carry the assigned sender UUID; a second Handshake or
a wrong sender UUID disconnects the peer (websocket.rs:66-111,163-170).
Text frames are ignored; liveness is the stream itself (no heartbeat
staleness).
"""

from __future__ import annotations

import asyncio
import logging
import uuid as uuid_mod

from websockets.asyncio.server import serve
from websockets.exceptions import ConnectionClosed
from websockets.protocol import State

from ..protocol import (
    DeserializeError,
    Instruction,
    Message,
    deserialize_message,
    serialize_message,
)
from ..engine.peers import FramedPayload, Peer
from ..robustness import failpoints
from ..robustness.failpoints import FailpointError
from .ws_framing import ws_binary_frame

logger = logging.getLogger(__name__)

#: transport write-buffer bound for the sync fast path. Below it,
#: fan-out frames go straight to the asyncio transport buffer (TCP
#: applies upstream backpressure); a peer that lets it grow past the
#: bound is a dead-or-pathological consumer and is EVICTED — the
#: reference's failed-send semantics (outgoing.rs:66-76; its zmq relay
#: channel is likewise unbounded below failure). A mid-range buffer
#: never triggers an awaited per-frame fallback: that path is ~10x
#: slower and one slow peer would stall the whole tick's delivery.
_WRITE_HARD_LIMIT = 8 << 20


# ws_binary_frame moved to transports/ws_framing.py (dependency-free
# so delivery workers can frame without the websockets import); the
# re-export above keeps this module's historical import surface.


class WebSocketTransport:
    def __init__(self, server):
        self.server = server
        self._ws_server = None
        # strong refs to eviction tasks: the loop keeps only weak ones,
        # and a GC'd task would silently skip the peer_map removal
        self._evictions: set = set()
        # uuid → connection for peers handed off to delivery workers:
        # on_peer_removed aborts the parent-side connection (the worker
        # owns the write half; the parent only reads)
        self._handed_off: dict = {}

    async def start(self) -> None:
        config = self.server.config
        # compression=None: the fan-out fast path writes raw frames
        # below (uncompressed frames are always legal, but negotiating
        # deflate would buy nothing and cost per-frame state), and
        # FlatBuffers payloads don't compress usefully anyway
        extra = {}
        if getattr(self.server, "delivery_plane", None) is not None:
            # worker-owned writes: the parent must never interleave
            # bytes on a handed-off socket, so the library's keepalive
            # pings are disabled — liveness is the read half (stream
            # EOF), same as a plain WS peer's
            extra["ping_interval"] = None
        self._ws_server = await serve(
            self._handle_connection,
            config.ws_host,
            config.ws_port,
            max_size=config.max_message_size,
            compression=None,
            **extra,
        )
        logger.info(
            "WebSocket server listening on %s:%s", config.ws_host, config.ws_port
        )

    async def stop(self) -> None:
        if self._ws_server is not None:
            self._ws_server.close()
            await self._ws_server.wait_closed()
            self._ws_server = None

    async def _handle_connection(self, connection) -> None:
        addr = "%s:%s" % (connection.remote_address or ("?", "?"))[:2]
        peer_uuid = uuid_mod.uuid4()
        provisional_uuid = peer_uuid
        registered = False
        sessions = getattr(self.server, "sessions", None)
        peer = None
        try:
            # Server-assigned UUID handshake (websocket.rs:51-63). With
            # sessions enabled the frame also carries a freshly minted
            # resume token as ``flex`` (``--session-ttl 0`` keeps the
            # reference-shaped frame byte for byte).
            token = None
            if sessions is not None:
                token = sessions.mint(peer_uuid, "websocket").token
            await connection.send(
                serialize_message(
                    Message(
                        instruction=Instruction.HANDSHAKE,
                        parameter=str(peer_uuid),
                        flex=token.encode() if token is not None else None,
                    )
                )
            )

            # The handshake phase reads exactly one frame: anything but a
            # valid Handshake drops the connection (websocket.rs:66-87).
            first = await self._next_message(
                connection, peer_uuid, addr, ignore_retries=False
            )
            if first is None or first.instruction != Instruction.HANDSHAKE:
                logger.debug("peer %s did not complete handshake", addr)
                return

            # Session resume: the echo presents a previously minted
            # token as ``flex`` — the connection rebinds to the parked
            # peer's UUID and state instead of serving as a new peer.
            session = None
            if sessions is not None and first.flex:
                session = sessions.peek(first.flex)

            # Storm-safe admission (ISSUE 12): classified new-vs-resume
            # once the echo identifies the peer; a refusal replies with
            # a jittered retry-after Handshake and closes — before any
            # registration or fd-handoff work.
            governor = getattr(self.server, "governor", None)
            if governor is not None:
                admitted, retry_ms = governor.admit_handshake(
                    resume=session is not None
                )
                if not admitted:
                    self.server.metrics.inc("ws.handshakes_refused")
                    await connection.send(serialize_message(Message(
                        instruction=Instruction.HANDSHAKE,
                        parameter=f"retry-after:{retry_ms}",
                    )))
                    return

            old = None
            if session is not None:
                # the provisional session minted for the assigned UUID
                # is dead weight once the echo proves a resume
                sessions.discard(provisional_uuid)
                old = self.server.prepare_rebind(session.uuid)
                peer_uuid = session.uuid

            def _writable() -> bool:
                """OPEN + healthy buffer; a peer past the hard limit
                is evicted (failed-send semantics, outgoing.rs:66-76)."""
                transport = connection.transport
                if (connection.state is not State.OPEN
                        or transport is None or transport.is_closing()):
                    return False
                if transport.get_write_buffer_size() > _WRITE_HARD_LIMIT:
                    logger.info(
                        "[%s] write buffer over %d bytes — evicting",
                        addr, _WRITE_HARD_LIMIT,
                    )
                    # abort() drops the buffered megabytes and closes
                    # the socket NOW — the recv loop exits and its
                    # finally runs the map removal too; the task makes
                    # the removal prompt rather than
                    # next-inbound-frame-delayed
                    self.server.metrics.inc("peers.evicted_overflow")
                    task = asyncio.get_running_loop().create_task(  # wql: allow(unsupervised-task)
                        self.server.peer_map.remove_if(peer_uuid, peer)
                    )
                    self._evictions.add(task)
                    task.add_done_callback(self._evictions.discard)
                    transport.abort()
                    return False
                return True

            def try_write(framed: FramedPayload) -> bool:
                """Sync fast path: hand the (shared) complete frame to
                the asyncio transport buffer. Both this and the
                library's ``send`` write whole frames atomically, so
                the paths interleave safely."""
                if not _writable():
                    return False
                frame = framed.cache.get("ws")
                if frame is None:
                    frame = ws_binary_frame(framed.payload)
                    framed.cache["ws"] = frame
                connection.transport.write(frame)
                return True

            def try_write_many(framed_list) -> bool:
                """Whole per-tick outbox in ONE coalesced transport
                write (``writelines`` — writev-style)."""
                if not _writable():
                    return False
                frames = []
                for framed in framed_list:
                    frame = framed.cache.get("ws")
                    if frame is None:
                        frame = ws_binary_frame(framed.payload)
                        framed.cache["ws"] = frame
                    frames.append(frame)
                connection.transport.writelines(frames)
                return True

            async def send_raw(data) -> None:
                failpoints.fire("transport.send")
                await connection.send(data)

            peer = Peer(
                uuid=peer_uuid,
                addr=addr,
                send_raw=send_raw,
                kind="websocket",
                tracks_heartbeat=False,
                try_write=try_write,
                try_write_many=try_write_many,
            )
            # Delivery-plane handoff (delivery/plane.py): pass the raw
            # TCP fd to a sender worker, which owns ALL writes from
            # here (adopt rebinds the peer's write paths onto its
            # ring). Safe at this point in the handshake: the client's
            # echo frame above proves our Handshake bytes already
            # reached it, so the parent's write buffer is empty and
            # nothing else has been queued (the peer is not yet in the
            # map, so no broadcast has targeted it). The parent keeps
            # the READ half — inbound frames still flow through this
            # loop. Degraded plane (no live worker) falls back to the
            # parent-owned fast path above.
            plane = getattr(self.server, "delivery_plane", None)
            if plane is not None:
                raw_sock = connection.transport.get_extra_info("socket")
                if raw_sock is not None and plane.adopt(
                    peer, fd=raw_sock.fileno()
                ):
                    self._handed_off[peer_uuid] = connection
            if session is not None:
                sessions.resume(session)
                if old is not None:
                    # resume over a still-registered stale binding:
                    # survivor-invisible swap (no Disconnect/Connect)
                    self.server.peer_map.rebind(peer)
                else:
                    await self.server.peer_map.insert(peer)
                logger.info(
                    "[%s] websocket session resumed for %s",
                    addr, peer_uuid,
                )
            else:
                await self.server.peer_map.insert(peer)
            registered = True

            while True:
                message = await self._next_message(connection, peer_uuid, addr)
                if message is None:
                    return
                if message.instruction == Instruction.HANDSHAKE:
                    # Duplicate handshake ⇒ disconnect (websocket.rs:108-111).
                    return
                try:
                    tracer = getattr(self.server, "tracer", None)
                    if tracer is not None and tracer.enabled:
                        # the router's handle span nests inside, so one
                        # trace covers recv→decode (in _next_message's
                        # loose span) and route→handle here
                        with tracer.span(
                            "ws.route", type=message.instruction.name
                        ):
                            await self.server.router.handle_message(message)
                    else:
                        await self.server.router.handle_message(message)
                except Exception:
                    # same per-message containment as the ZMQ loop: a
                    # poison message must cost one message, not the
                    # connection
                    self.server.metrics.inc("ws.recv_errors")
                    logger.exception(
                        "error processing websocket message — dropped"
                    )
        except ConnectionClosed:
            pass
        except Exception:
            logger.exception("websocket connection error: %s", addr)
        finally:
            if self._handed_off.get(peer_uuid) is connection:
                # guard: a resume may have handed a NEWER connection
                # off under the same uuid — never pop that one
                self._handed_off.pop(peer_uuid, None)
            if registered:
                # only while this connection is still the CURRENT
                # binding — a resumed session's fresh binding must not
                # be evicted by its predecessor's teardown
                await self.server.peer_map.remove_if(peer_uuid, peer)
            elif sessions is not None:
                # never-registered connection: drop the provisional
                # session minted for the assigned UUID (a resumed
                # session stays parked for its TTL instead)
                sessions.discard(provisional_uuid)

    def on_peer_removed(self, peer_uuid: uuid_mod.UUID) -> None:
        """PeerMap removal hook: for a peer handed off to a delivery
        worker, abort the parent-side connection (no close frame — the
        worker owns the write half and closes its fd on the shard's
        ``remove``; a library close here could interleave bytes
        mid-frame). The recv loop's finally does the map removal."""
        connection = self._handed_off.pop(peer_uuid, None)
        if connection is not None and connection.transport is not None:
            connection.transport.abort()

    async def _next_message(
        self,
        connection,
        peer_uuid: uuid_mod.UUID,
        addr: str,
        ignore_retries: bool = True,
    ) -> Message | None:
        """Read frames until a valid binary Message arrives; None on
        close or sender-UUID violation (websocket.rs:137-173). With
        ``ignore_retries=False`` an ignorable frame returns None too."""
        while True:
            try:
                frame = await connection.recv()
            except ConnectionClosed:
                return None
            if isinstance(frame, str):
                if ignore_retries:
                    continue  # non-binary → ignore
                return None
            try:
                failpoints.fire("codec.decode")
                tracer = getattr(self.server, "tracer", None)
                if tracer is not None and tracer.enabled:
                    with tracer.span("ws.decode", bytes=len(frame)):
                        message = deserialize_message(frame)
                else:
                    message = deserialize_message(frame)
            except (DeserializeError, FailpointError):
                logger.debug("deserialize error from peer: %s", addr)
                if ignore_retries:
                    continue
                return None
            if message.sender_uuid != peer_uuid:
                logger.debug(
                    "peer uuid incorrect: expected %s, got %s",
                    peer_uuid,
                    message.sender_uuid,
                )
                return None  # wrong sender ⇒ close
            return message
