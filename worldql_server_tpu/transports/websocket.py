"""WebSocket transport.

Rebuild of the reference's WS server
(worldql_server/src/transport/http/websocket.rs): the *server* assigns
the peer UUID (contrast ZeroMQ, where the client picks), sends a
client-bound Handshake carrying that UUID as ``parameter``, and
requires the client's first frame to be a Handshake echo with the
assigned UUID as sender. After that, every binary frame must
deserialize and carry the assigned sender UUID; a second Handshake or
a wrong sender UUID disconnects the peer (websocket.rs:66-111,163-170).
Text frames are ignored; liveness is the stream itself (no heartbeat
staleness).
"""

from __future__ import annotations

import asyncio
import logging
import uuid as uuid_mod

from websockets.asyncio.server import serve
from websockets.exceptions import ConnectionClosed

from ..protocol import (
    DeserializeError,
    Instruction,
    Message,
    deserialize_message,
    serialize_message,
)
from ..engine.peers import Peer

logger = logging.getLogger(__name__)


class WebSocketTransport:
    def __init__(self, server):
        self.server = server
        self._ws_server = None

    async def start(self) -> None:
        config = self.server.config
        self._ws_server = await serve(
            self._handle_connection,
            config.ws_host,
            config.ws_port,
            max_size=config.max_message_size,
        )
        logger.info(
            "WebSocket server listening on %s:%s", config.ws_host, config.ws_port
        )

    async def stop(self) -> None:
        if self._ws_server is not None:
            self._ws_server.close()
            await self._ws_server.wait_closed()
            self._ws_server = None

    async def _handle_connection(self, connection) -> None:
        addr = "%s:%s" % (connection.remote_address or ("?", "?"))[:2]
        peer_uuid = uuid_mod.uuid4()
        registered = False
        try:
            # Server-assigned UUID handshake (websocket.rs:51-63).
            await connection.send(
                serialize_message(
                    Message(
                        instruction=Instruction.HANDSHAKE,
                        parameter=str(peer_uuid),
                    )
                )
            )

            # The handshake phase reads exactly one frame: anything but a
            # valid Handshake drops the connection (websocket.rs:66-87).
            first = await self._next_message(
                connection, peer_uuid, addr, ignore_retries=False
            )
            if first is None or first.instruction != Instruction.HANDSHAKE:
                logger.debug("peer %s did not complete handshake", addr)
                return

            peer = Peer(
                uuid=peer_uuid,
                addr=addr,
                send_raw=connection.send,
                kind="websocket",
                tracks_heartbeat=False,
            )
            await self.server.peer_map.insert(peer)
            registered = True

            while True:
                message = await self._next_message(connection, peer_uuid, addr)
                if message is None:
                    return
                if message.instruction == Instruction.HANDSHAKE:
                    # Duplicate handshake ⇒ disconnect (websocket.rs:108-111).
                    return
                await self.server.router.handle_message(message)
        except ConnectionClosed:
            pass
        except Exception:
            logger.exception("websocket connection error: %s", addr)
        finally:
            if registered:
                await self.server.peer_map.remove(peer_uuid)

    async def _next_message(
        self,
        connection,
        peer_uuid: uuid_mod.UUID,
        addr: str,
        ignore_retries: bool = True,
    ) -> Message | None:
        """Read frames until a valid binary Message arrives; None on
        close or sender-UUID violation (websocket.rs:137-173). With
        ``ignore_retries=False`` an ignorable frame returns None too."""
        while True:
            try:
                frame = await connection.recv()
            except ConnectionClosed:
                return None
            if isinstance(frame, str):
                if ignore_retries:
                    continue  # non-binary → ignore
                return None
            try:
                message = deserialize_message(frame)
            except DeserializeError:
                logger.debug("deserialize error from peer: %s", addr)
                if ignore_retries:
                    continue
                return None
            if message.sender_uuid != peer_uuid:
                logger.debug(
                    "peer uuid incorrect: expected %s, got %s",
                    peer_uuid,
                    message.sender_uuid,
                )
                return None  # wrong sender ⇒ close
            return message
