"""ZeroMQ transport.

Rebuild of the reference's asymmetric socket pattern
(worldql_server/src/transport/zeromq/): the server binds one PULL
socket for all inbound traffic (incoming.rs:19-24); each client runs
its own PULL and the server connects a dedicated PUSH socket *back* to
an address the client supplies as the Handshake ``parameter``
(outgoing.rs:95-118).

Handshake flow: a message from an unknown sender UUID is dropped unless
it is a Handshake carrying an address parameter; the server then
connects a PUSH socket to ``tcp://<parameter>``, echoes a bare
Handshake (nil sender, no parameter — outgoing.rs:108-118), and
registers the peer. Known senders' Handshakes are swallowed
(incoming.rs:56-61); UUID clashes drop the handshake
(outgoing.rs:88-94). ZMQ peers are heartbeat-tracked: the engine's
staleness sweeper evicts them (outgoing.rs:28-47,132-150), and a failed
send evicts immediately (outgoing.rs:66-76).

Session continuity (``--session-ttl``, robustness/sessions.py): the
handshake echo's ``parameter`` carries a minted session token; a
reconnecting client presents it as ``flex`` on its Handshake and the
server rebinds the new connect-back to the parked state — valid even
while the stale old binding is still registered (the server has not
yet noticed the drop). Handshakes are also a governor admission class
(``--overload on``): a refused handshake gets a one-shot jittered
``retry-after:<ms>`` Handshake on its connect-back address (budgeted —
the refusal path must not become a reflector) and no registration
work happens at all.
"""

from __future__ import annotations

import asyncio
import logging
import uuid as uuid_mod

import zmq
import zmq.asyncio

from ..engine.peers import Peer
from ..protocol.entity_wire import RECV_DRAIN_MAX
from ..protocol import (
    DeserializeError,
    Instruction,
    Message,
    deserialize_message,
    serialize_message,
)
from ..robustness import failpoints

logger = logging.getLogger(__name__)


def _valid_socket_addr(parameter: str) -> bool:
    """The reference parses the parameter as a SocketAddr
    (outgoing.rs:97-103): ``ip:port`` (IPv4 or bracketed IPv6)."""
    import ipaddress

    host, sep, port = parameter.rpartition(":")
    if not sep or not host:
        return False
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    try:
        ipaddress.ip_address(host)
    except ValueError:
        return False
    return port.isdigit() and 0 < int(port) < 65536


class ZmqTransport:
    def __init__(self, server):
        self.server = server
        self.ctx = zmq.asyncio.Context()
        self._pull: zmq.asyncio.Socket | None = None
        self._push_sockets: dict[uuid_mod.UUID, zmq.asyncio.Socket] = {}
        self._recv_task: asyncio.Task | None = None
        self._recv_handle = None  # SupervisedTask under a supervisor
        # Failed-send evictions run as tasks; the loop only weak-refs
        # running tasks, so retain them or a GC pass could drop an
        # eviction mid-flight and leak the dead peer from the map.
        self._evictions: set[asyncio.Task] = set()

    async def start(self) -> None:
        config = self.server.config
        self._pull = self.ctx.socket(zmq.PULL)
        # Bound inbound frames BEFORE bind: without MAXMSGSIZE a single
        # hostile peer can stream an arbitrarily large message into
        # server memory (libzmq buffers the whole frame). Oversized
        # senders are disconnected by libzmq; the PULL socket and every
        # other peer keep working.
        self._pull.setsockopt(zmq.MAXMSGSIZE, config.max_message_size)
        self._pull.bind(f"tcp://{config.zmq_server_host}:{config.zmq_server_port}")
        logger.info(
            "ZeroMQ PULL server listening on %s:%s",
            config.zmq_server_host,
            config.zmq_server_port,
        )
        supervisor = getattr(self.server, "supervisor", None)
        if supervisor is not None:
            # CRITICAL: a permanently dead recv loop is a silently deaf
            # transport — restart within budget, then escalate
            self._recv_handle = supervisor.spawn(
                "zmq-recv", self._recv_loop, critical=True
            )
        else:
            self._recv_task = asyncio.create_task(self._recv_loop(), name="zmq-pull")  # wql: allow(unsupervised-task)

    async def stop(self) -> None:
        if self._recv_handle is not None:
            await self._recv_handle.stop()
            self._recv_handle = None
        if self._recv_task is not None:
            self._recv_task.cancel()
            try:
                await self._recv_task
            except (asyncio.CancelledError, Exception):
                pass
            self._recv_task = None
        for sock in self._push_sockets.values():
            sock.close(linger=0)
        self._push_sockets.clear()
        if self._pull is not None:
            self._pull.close(linger=0)
            self._pull = None
        self.ctx.term()

    async def _recv_loop(self) -> None:
        """PULL loop (incoming.rs:26-75): multipart frames are
        concatenated, deserialized-or-dropped, then routed.

        Columnar drain (--entity-sim + native codec): everything the
        socket already holds — bounded by ``RECV_DRAIN_MAX`` — drains
        into ONE recv batch handed to ``ColumnarIngest.process_batch``,
        which batch-decodes every entity-update message straight into
        the plane's SoA columns and routes the rest through
        ``_route_data`` in arrival order. Without the fast path the
        loop is the per-message path it always was.

        Per-message crash containment: ANY exception escaping the
        processing of one message (a router bug a hostile payload
        tickles, a handshake connect error) drops THAT message —
        logged and counted in ``zmq.recv_errors`` — and the loop keeps
        receiving. Before this, one poison message permanently deafened
        the transport while the process kept running. Faults in the
        receive machinery itself (socket teardown, the `zmq.recv`
        failpoint) still escape and are the supervisor's job."""
        assert self._pull is not None
        limit = self.server.config.max_message_size
        while True:
            # outside the containment: kills the LOOP, exercising the
            # supervisor's restart/escalate policy in the chaos suite
            failpoints.fire("zmq.recv")
            parts = await self._pull.recv_multipart()
            fast = getattr(self.server, "entity_ingest", None)
            if fast is None or not fast.active:
                try:
                    await self._process_inbound(parts, limit)
                except Exception:
                    self.server.metrics.inc("zmq.recv_errors")
                    logger.exception(
                        "error processing inbound zmq message — dropped"
                    )
                continue
            # Clustered shards receive router-framed bytes (the WQTX
            # trace prefix, cluster/tracectx.py). Strip it BEFORE the
            # native entity classifier — a prefixed buffer fails
            # classification and the whole batch degrades to the
            # object path (PR 15's KNOWN GAP, closed here) — and
            # carry the ctx alongside so slow-routed messages still
            # thread trace_ctx onto their Message.
            cluster = getattr(self.server, "cluster", None)
            datas = []
            ctxs: list[tuple[int, int]] | None = \
                [] if cluster is not None else None
            unwrapped = 0
            data = self._flatten(parts, limit)
            if data is not None:
                unwrapped += await self._absorb_inbound(
                    cluster, data, datas, ctxs
                )
            while len(datas) < RECV_DRAIN_MAX:
                try:
                    parts = await self._pull.recv_multipart(zmq.NOBLOCK)
                except zmq.Again:
                    break
                data = self._flatten(parts, limit)
                if data is not None:
                    unwrapped += await self._absorb_inbound(
                        cluster, data, datas, ctxs
                    )
            if unwrapped:
                # the fast-path-through-router proof: router-framed
                # messages reaching the columnar batch pre-unwrapped
                self.server.metrics.inc("zmq.ctx_unwrapped", unwrapped)
            if datas:
                # contains per message internally; never raises
                await fast.process_batch(datas, self._route_data,
                                         ctxs=ctxs)

    async def _absorb_inbound(self, cluster, data: bytes, datas: list,
                              ctxs: list | None) -> int:
        """Classify one inbound frame for the columnar batch. Live
        resharding (cluster/resharding) adds two diverts ahead of the
        fast path: freeze FENCE frames ack over control instead of
        decoding, and STALE-EPOCH frames (stamped under an older
        placement than this shard holds) take the full decode +
        ownership check — a stale entity frame must never reach the
        SoA columns directly, it may belong to a world this shard just
        lost. Everything else joins the batch with its trace ctx in
        lockstep. Returns 1 when a live trace ctx was stripped."""
        if cluster is None:
            datas.append(data)  # wql: allow(unbounded-ingest) — bounded by RECV_DRAIN_MAX in the caller
            return 0
        trace_id, t_ctx, epoch, data = cluster.unwrap(data)
        if data[:4] == cluster.FENCE_MAGIC:
            cluster.on_fence(data)
            return 0
        if cluster.frame_stale(epoch):
            await self._route_data(
                data, ctx=(trace_id, t_ctx), epoch=epoch
            )
            return 0
        ctxs.append((trace_id, t_ctx))  # wql: allow(unbounded-ingest) — lockstep with datas, same RECV_DRAIN_MAX bound
        datas.append(data)  # wql: allow(unbounded-ingest) — bounded by RECV_DRAIN_MAX; admission happens in ColumnarIngest/router
        return 1 if trace_id else 0

    def _flatten(self, parts: list[bytes], limit: int) -> bytes | None:
        """Bound + join one multipart message (None = dropped).
        MAXMSGSIZE bounds each PART; bound the flattened total before
        the join materializes it a second time. (libzmq assembles
        multipart atomically before delivery, so its own buffering of
        many under-cap parts cannot be bounded by any socket option —
        see Config.max_message_size.)"""
        if sum(len(p) for p in parts) > limit:
            logger.warning(
                "dropping oversized multipart zmq message (%d parts)",
                len(parts),
            )
            return None
        return b"".join(parts)

    async def _process_inbound(self, parts: list[bytes], limit: int) -> None:
        """One inbound multipart message: bound, decode, route."""
        data = self._flatten(parts, limit)
        if data is not None:
            await self._route_data(data)

    async def _route_data(self, data: bytes,
                          ctx: tuple[int, int] | None = None,
                          epoch: int = 0) -> None:
        tracer = getattr(self.server, "tracer", None)
        if tracer is not None and tracer.enabled:
            # recv→decode→route under one span tree: the decode and the
            # router's handle span nest inside "zmq.recv", so a slow
            # inbound message shows WHERE it spent its wall time
            with tracer.span("zmq.recv", bytes=len(data)) as rspan:
                await self._decode_route(data, tracer, rspan, ctx=ctx,
                                         epoch=epoch)
        else:
            await self._decode_route(data, None, ctx=ctx, epoch=epoch)

    async def _decode_route(self, data: bytes, tracer, rspan=None,
                            ctx: tuple[int, int] | None = None,
                            epoch: int = 0) -> None:
        # Cluster shards receive every message through the router,
        # which frames a trace context on (cluster/tracectx.py):
        # strip it BEFORE the codec (fan-out re-broadcasts the
        # unwrapped bytes) and thread it onto the Message so delivery
        # closes the router-ingress clock at socket-write-complete.
        # The columnar recv loop unwraps pre-batch (the native
        # classifier needs bare wire bytes) and passes the ctx in;
        # the per-message path unwraps here. Non-cluster servers pay
        # one attribute test.
        if ctx is not None:
            trace_id, t_ctx = ctx
        else:
            cluster = getattr(self.server, "cluster", None)
            trace_id = t_ctx = 0
            if cluster is not None:
                trace_id, t_ctx, epoch, data = cluster.unwrap(data)
                if data[:4] == cluster.FENCE_MAGIC:
                    # freeze fence on the per-message path (no columnar
                    # fast path armed): ack over control, never decode
                    cluster.on_fence(data)
                    return
        try:
            failpoints.fire("codec.decode")
            if tracer is not None:
                with tracer.span("codec.decode"):
                    message = deserialize_message(data)
            else:
                message = deserialize_message(data)
        except DeserializeError:
            logger.debug("dropping invalid zmq message: deserialize error")
            return
        if epoch:
            # live resharding: a frame stamped under an older placement
            # epoch, for a world/peer this shard no longer owns, bounces
            # back to the router as a re-route hint instead of mutating
            # state the placement already moved away
            cluster = getattr(self.server, "cluster", None)
            if (
                cluster is not None
                and cluster.frame_stale(epoch)
                and cluster.frame_misrouted(message, epoch)
            ):
                return
        if trace_id:
            message.trace_ctx = (trace_id, t_ctx)
            if rspan is not None:
                # the cross-process chain key: this span tree carries
                # the same trace id the router's forward span and the
                # remote shard's stitched ring spans carry
                rspan.tag(trace_id=format(trace_id, "016x"))

        if message.sender_uuid in self.server.peer_map:
            if message.instruction != Instruction.HANDSHAKE:
                await self.server.router.handle_message(message)
                return
            # known-sender handshakes are swallowed (incoming.rs:56-61)
            # UNLESS a valid session token rides along: the client is
            # resuming over a stale binding the server has not yet
            # noticed dropping — rebind instead of ignoring
            sessions = getattr(self.server, "sessions", None)
            if sessions is None or sessions.peek(
                message.flex, message.sender_uuid
            ) is None:
                return
            await self._handle_handshake(message)
            return

        if (
            message.instruction != Instruction.HANDSHAKE
            or message.parameter is None
        ):
            return  # unknown sender, not a handshake → ignore

        await self._handle_handshake(message)

    async def _handle_handshake(self, message: Message) -> None:
        """Connect-back PUSH + handshake echo + registration or
        session resume (outgoing.rs:81-130). Admission runs BEFORE any
        connect-back/socket work — a shed handshake costs one decode."""
        sessions = getattr(self.server, "sessions", None)
        session = None
        if sessions is not None:
            session = sessions.peek(message.flex, message.sender_uuid)
        if message.sender_uuid in self.server.peer_map and session is None:
            return  # clashing UUID → drop

        parameter = message.parameter
        if parameter is None or not _valid_socket_addr(parameter):
            return  # invalid socket address → drop
        endpoint = f"tcp://{parameter}"

        # Storm-safe admission (ISSUE 12): new connects shed before
        # resumes; REJECT still admits resumes up to the governor's
        # token bucket. Refusals get a budgeted jittered retry-after
        # hint on the address the client just supplied.
        governor = getattr(self.server, "governor", None)
        if governor is not None:
            admitted, retry_ms = governor.admit_handshake(
                resume=session is not None
            )
            if not admitted:
                await self._send_refusal(endpoint, retry_ms, governor)
                return

        logger.debug("zeromq peer address: %s", endpoint)
        peer_uuid = message.sender_uuid

        token = None
        if sessions is not None:
            if session is not None:
                token = session.token
            else:
                if sessions.get(peer_uuid) is not None:
                    # tokenless handshake for a UUID with held state:
                    # that state belongs to the TOKEN holder — tear it
                    # down first; this is a brand-new peer
                    self.server._teardown_peer_state(peer_uuid)
                token = sessions.mint(peer_uuid, "zeromq").token

        push = self.ctx.socket(zmq.PUSH)
        push.setsockopt(zmq.LINGER, 0)
        push.connect(endpoint)

        # Handshake echo: nil sender (outgoing.rs:108-118); with
        # sessions enabled the parameter carries the resume token
        # (``--session-ttl 0`` keeps the bare no-parameter echo).
        await push.send(
            serialize_message(
                Message(instruction=Instruction.HANDSHAKE, parameter=token)
            )
        )

        async def send_raw(data: bytes) -> None:
            sock = self._push_sockets.get(peer_uuid)
            if sock is None:
                raise ConnectionError("push socket gone")
            try:
                failpoints.fire("transport.send")
                await sock.send(data)
            except Exception:
                # Failed send ⇒ evict peer (outgoing.rs:66-76) — but
                # only while THIS binding is still current: a stale
                # binding's dying send must not evict a resumed one.
                self.server.metrics.inc("peers.evicted_send_failed")
                self._drop_socket(peer_uuid)
                task = asyncio.get_running_loop().create_task(  # wql: allow(unsupervised-task)
                    self.server.peer_map.remove_if(peer_uuid, peer)
                )
                self._evictions.add(task)
                task.add_done_callback(self._evictions.discard)
                raise

        old = None
        if session is not None:
            # Resume: silently drop the stale old binding (connect-back
            # socket, delivery shard slot) — parked state untouched —
            # so the fresh binding below can take its place, possibly
            # on a different shard.
            old = self.server.prepare_rebind(peer_uuid)

        peer = Peer(
            uuid=peer_uuid,
            addr=parameter,
            send_raw=send_raw,
            kind="zeromq",
            tracks_heartbeat=True,
        )
        plane = getattr(self.server, "delivery_plane", None)
        adopted = plane is not None and plane.adopt(peer, endpoint=endpoint)
        if adopted:
            # the owning sender worker connects its OWN PUSH to the
            # peer's PULL; the parent's echo socket closes once the
            # handshake echo flushes (bounded linger) — from here on
            # every frame for this peer rides the worker's shard
            push.close(linger=2000)
        else:
            # single-process mode (or degraded plane): the parent owns
            # the socket, reference semantics unchanged
            self._push_sockets[peer_uuid] = push
        if session is not None:
            sessions.resume(session)
            if old is not None:
                # resume over a still-registered stale binding: the
                # swap is survivor-invisible (no Disconnect/Connect)
                self.server.peer_map.rebind(peer)
            else:
                # parked resume: PeerDisconnect was broadcast at park
                # time, so the rebind announces like a connect
                await self.server.peer_map.insert(peer)
            logger.info(
                "[%s] zeromq session resumed for %s", parameter, peer_uuid
            )
        else:
            await self.server.peer_map.insert(peer)

    async def _send_refusal(self, endpoint: str, retry_ms: int,
                            governor) -> None:
        """One-shot refusal hint: a Handshake whose parameter is
        ``retry-after:<ms>`` pushed to the refused client's own
        connect-back address, within the governor's hint budget —
        beyond it the refusal is silent (cheapest possible shed)."""
        self.server.metrics.inc("zmq.handshakes_refused")
        if not governor.take_refusal_hint():
            return
        push = self.ctx.socket(zmq.PUSH)
        push.setsockopt(zmq.LINGER, 200)
        try:
            push.connect(endpoint)
            await push.send(serialize_message(Message(
                instruction=Instruction.HANDSHAKE,
                parameter=f"retry-after:{retry_ms}",
            )))
            self.server.metrics.inc("zmq.refusal_hints")
        except Exception:
            logger.debug("refusal hint to %s failed", endpoint)
        finally:
            push.close(linger=200)

    def _drop_socket(self, peer_uuid: uuid_mod.UUID) -> None:
        sock = self._push_sockets.pop(peer_uuid, None)
        if sock is not None:
            sock.close(linger=0)

    def on_peer_removed(self, peer_uuid: uuid_mod.UUID) -> None:
        """PeerMap removal hook: close the connect-back PUSH socket."""
        self._drop_socket(peer_uuid)
