"""Spatial quantization: f64 positions → integer cube / region labels.

Two distinct grids exist, with deliberately different conventions, both
matching the reference bit-for-bit:

* **Subscription cubes** (``coord_clamp``): cubes are labeled by their
  *max corner*, sign-symmetric so positive and negative space never
  share a cube, and exact 0.0 maps to ``+size``
  (worldql_server/src/subscriptions/cube_area.rs:23-44).

* **DB regions** (``clamp_region_coord``): regions are labeled by a
  floor-style corner; 0.0 maps to 0, and negative coordinates always
  round *away* from zero — including exact negative multiples, which
  shift one full region further down (e.g. -16 @ size 16 → -32)
  (worldql_server/src/database/world_region.rs:93-110).

Scalar functions are the semantic reference; ``*_batch`` variants are
vectorized numpy float64 used on the request hot path. Quantization
always runs host-side in f64 — the device only ever sees integer cell
labels, so TPU fast-math can never perturb grid assignment.
"""

from __future__ import annotations

import math

import numpy as np

from ..utils.rounding import round_by_multiple

_I64_MAX = 2**63 - 1
_I64_MIN = -(2**63)
_I64_MAX_F = float(_I64_MAX)  # 9.223372036854776e18
_I64_MIN_F = float(_I64_MIN)


def _as_i64(f: float) -> int:
    """Rust `f64 as i64` saturating cast: NaN → 0, out-of-range clamps."""
    if math.isnan(f):
        return 0
    if f >= _I64_MAX_F:
        return _I64_MAX
    if f <= _I64_MIN_F:
        return _I64_MIN
    return int(f)


def _sat_add(a: int, b: int) -> int:
    return max(_I64_MIN, min(_I64_MAX, a + b))


# region: scalar reference implementations


def coord_clamp(coord: float, size: int) -> int:
    """Quantize one subscription-cube coordinate (cube_area.rs:23-44).

    Total function: casts saturate like Rust's ``as i64`` (NaN → cube
    ``+size`` by the same arithmetic the reference executes; ±inf
    saturates to ±i64::MAX instead of the reference's release-mode
    integer wrap, which is the only divergence and only at ±inf).
    """
    if math.isinf(coord):
        return _I64_MAX if coord > 0 else -_I64_MAX

    abs_coord = abs(coord)
    multiplier = -1 if coord < 0.0 else 1  # NaN compares false → +1

    # Exact non-zero multiples label their own cube.
    if not math.isnan(coord):
        if math.fmod(abs_coord, float(size)) == 0.0 and coord != 0.0:
            return _as_i64(coord)

    rounded = round_by_multiple(abs_coord, float(size))
    if rounded > coord:  # NaN > NaN is false → falls to +size, like Rust
        result = _as_i64(rounded)
    else:
        result = _sat_add(_as_i64(rounded), size)

    return result * multiplier


def cube_coords(x: float, y: float, z: float, size: int) -> tuple[int, int, int]:
    """Vector3 → CubeArea (cube_area.rs:50-56)."""
    return (coord_clamp(x, size), coord_clamp(y, size), coord_clamp(z, size))


def clamp_region_coord(c: float, region_size: int) -> int:
    """Quantize one DB-region coordinate (world_region.rs:93-110).

    NaN raises ValueError: the reference recurses forever on NaN here
    (world_region.rs:104-109 — a stack overflow a hostile record could
    trigger); we refuse instead and let per-message isolation drop it.
    ±inf saturates like Rust's ``as i64``.
    """
    if math.isnan(c):
        raise ValueError("NaN region coordinate")
    if c == 0.0:
        return 0

    if c >= 0.0:
        ci = _as_i64(c)  # truncate toward zero, saturating
        return ci - ci % region_size  # ci >= 0: python % == trunc %
    # Negative: reflect, quantize, negate. Exact negative multiples land
    # one region further down — reference-exact behavior.
    return -clamp_region_coord(-c + float(region_size), region_size)


def region_coords(
    x: float, y: float, z: float, sx: int, sy: int, sz: int
) -> tuple[int, int, int]:
    """Vector3 → WorldRegion coords (world_region.rs:18-35)."""
    return (
        clamp_region_coord(x, sx),
        clamp_region_coord(y, sy),
        clamp_region_coord(z, sz),
    )


def clamp_table_size(c: int, table_size: int) -> int:
    """Snap a region coord to its containing table's min corner
    (world_region.rs:112-129). Note: unlike regions, exact negative
    table borders return themselves."""
    rem = math.fmod(c, table_size)  # trunc-style remainder, like Rust %
    if rem == 0:
        return c

    if c >= 0:
        return c - c % table_size
    return -clamp_table_size(-c + table_size, table_size)


def table_bounds(region_coord: int, table_size: int) -> tuple[int, int]:
    """(min, max) extent of the table containing a region coordinate
    (world_region.rs:38-59)."""
    lo = clamp_table_size(region_coord, table_size)
    return (lo, lo + table_size)


# endregion

# region: vectorized batch implementations


def _sat_i64_batch(x: np.ndarray) -> np.ndarray:
    """Vectorized Rust-style saturating f64 → i64 cast."""
    safe = np.where(np.isfinite(x) & (np.abs(x) < _I64_MAX_F), x, 0.0)
    out = safe.astype(np.int64)
    out = np.where(x >= _I64_MAX_F, np.int64(_I64_MAX), out)
    out = np.where(x <= _I64_MIN_F, np.int64(_I64_MIN), out)
    return np.where(np.isnan(x), np.int64(0), out)


def coord_clamp_batch(coords: np.ndarray, size: int) -> np.ndarray:
    """Vectorized ``coord_clamp`` over a float64 array → int64 array.
    Agrees with the scalar form on every input, including NaN/±inf and
    |coord| beyond i64 range (saturating-cast semantics)."""
    c = np.asarray(coords, dtype=np.float64)
    size_f = float(size)

    abs_c = np.abs(c)
    multiplier = np.where(c < 0.0, -1, 1).astype(np.int64)

    with np.errstate(invalid="ignore"):
        exact = (np.fmod(abs_c, size_f) == 0.0) & (c != 0.0)

        # round_by_multiple(abs_c, size) with the 0→size special case.
        rounded = np.ceil(abs_c / size_f) * size_f
        rounded = np.where(abs_c == 0.0, size_f, rounded)

        rounded_i = _sat_i64_batch(rounded)
        bumped = np.minimum(rounded_i, _I64_MAX - size) + size  # saturating +size
        result = np.where(rounded > c, rounded_i, bumped) * multiplier
        result = np.where(exact, _sat_i64_batch(c), result)

        # Specials, matching the scalar form exactly.
        result = np.where(np.isposinf(c), np.int64(_I64_MAX), result)
        result = np.where(np.isneginf(c), np.int64(-_I64_MAX), result)

    return result


def cube_coords_batch(positions: np.ndarray, size: int) -> np.ndarray:
    """[N, 3] float64 positions → [N, 3] int64 cube labels."""
    pos = np.asarray(positions, dtype=np.float64)
    return coord_clamp_batch(pos, size)


def clamp_region_coord_batch(coords: np.ndarray, region_size: int) -> np.ndarray:
    """Vectorized ``clamp_region_coord`` → int64 array. NaN raises
    ValueError (see the scalar form); ±inf saturates."""
    c = np.asarray(coords, dtype=np.float64)
    if np.isnan(c).any():
        raise ValueError("NaN region coordinate")

    def _positive(v: np.ndarray) -> np.ndarray:
        vi = _sat_i64_batch(v)  # truncation toward zero for v >= 0
        return vi - vi % np.int64(region_size)

    pos_result = _positive(np.maximum(c, 0.0))
    neg_result = -_positive(-c + float(region_size))

    result = np.where(c >= 0.0, pos_result, neg_result)
    return np.where(c == 0.0, np.int64(0), result)


def region_coords_batch(
    positions: np.ndarray, sx: int, sy: int, sz: int
) -> np.ndarray:
    """[N, 3] float64 positions → [N, 3] int64 region labels."""
    pos = np.asarray(positions, dtype=np.float64)
    out = np.empty(pos.shape, dtype=np.int64)
    out[..., 0] = clamp_region_coord_batch(pos[..., 0], sx)
    out[..., 1] = clamp_region_coord_batch(pos[..., 1], sy)
    out[..., 2] = clamp_region_coord_batch(pos[..., 2], sz)
    return out


# endregion
