"""Spatial quantization: f64 positions → integer cube / region labels.

Two distinct grids exist, with deliberately different conventions, both
matching the reference bit-for-bit:

* **Subscription cubes** (``coord_clamp``): cubes are labeled by their
  *max corner*, sign-symmetric so positive and negative space never
  share a cube, and exact 0.0 maps to ``+size``
  (worldql_server/src/subscriptions/cube_area.rs:23-44).

* **DB regions** (``clamp_region_coord``): regions are labeled by a
  floor-style corner; 0.0 maps to 0, and negative coordinates always
  round *away* from zero — including exact negative multiples, which
  shift one full region further down (e.g. -16 @ size 16 → -32)
  (worldql_server/src/database/world_region.rs:93-110).

Scalar functions are the semantic reference; ``*_batch`` variants are
vectorized numpy float64 used on the request hot path. Quantization
always runs host-side in f64 — the device only ever sees integer cell
labels, so TPU fast-math can never perturb grid assignment.
"""

from __future__ import annotations

import math

import numpy as np

from ..utils.rounding import round_by_multiple

# region: scalar reference implementations


def coord_clamp(coord: float, size: int) -> int:
    """Quantize one subscription-cube coordinate (cube_area.rs:23-44)."""
    abs_coord = abs(coord)
    multiplier = -1 if coord < 0.0 else 1

    # Exact non-zero multiples label their own cube (Rust `coord as i64`
    # truncates toward zero).
    if math.fmod(abs_coord, float(size)) == 0.0 and coord != 0.0:
        return int(coord)

    rounded = round_by_multiple(abs_coord, float(size))
    if rounded > coord:
        result = int(rounded)
    else:
        result = int(rounded) + size

    return result * multiplier


def cube_coords(x: float, y: float, z: float, size: int) -> tuple[int, int, int]:
    """Vector3 → CubeArea (cube_area.rs:50-56)."""
    return (coord_clamp(x, size), coord_clamp(y, size), coord_clamp(z, size))


def clamp_region_coord(c: float, region_size: int) -> int:
    """Quantize one DB-region coordinate (world_region.rs:93-110)."""
    if c == 0.0:
        return 0

    if c >= 0.0:
        ci = int(c)  # truncate toward zero
        return ci - ci % region_size  # ci >= 0: python % == trunc %
    # Negative: reflect, quantize, negate. Exact negative multiples land
    # one region further down — reference-exact behavior.
    return -clamp_region_coord(-c + float(region_size), region_size)


def region_coords(
    x: float, y: float, z: float, sx: int, sy: int, sz: int
) -> tuple[int, int, int]:
    """Vector3 → WorldRegion coords (world_region.rs:18-35)."""
    return (
        clamp_region_coord(x, sx),
        clamp_region_coord(y, sy),
        clamp_region_coord(z, sz),
    )


def clamp_table_size(c: int, table_size: int) -> int:
    """Snap a region coord to its containing table's min corner
    (world_region.rs:112-129). Note: unlike regions, exact negative
    table borders return themselves."""
    rem = math.fmod(c, table_size)  # trunc-style remainder, like Rust %
    if rem == 0:
        return c

    if c >= 0:
        return c - c % table_size
    return -clamp_table_size(-c + table_size, table_size)


def table_bounds(region_coord: int, table_size: int) -> tuple[int, int]:
    """(min, max) extent of the table containing a region coordinate
    (world_region.rs:38-59)."""
    lo = clamp_table_size(region_coord, table_size)
    return (lo, lo + table_size)


# endregion

# region: vectorized batch implementations


def coord_clamp_batch(coords: np.ndarray, size: int) -> np.ndarray:
    """Vectorized ``coord_clamp`` over a float64 array → int64 array."""
    c = np.asarray(coords, dtype=np.float64)
    size_f = float(size)

    abs_c = np.abs(c)
    multiplier = np.where(c < 0.0, -1, 1).astype(np.int64)

    exact = (np.fmod(abs_c, size_f) == 0.0) & (c != 0.0)

    # round_by_multiple(abs_c, size) with the 0→size special case.
    rounded = np.ceil(abs_c / size_f) * size_f
    rounded = np.where(abs_c == 0.0, size_f, rounded)

    result = np.where(rounded > c, rounded.astype(np.int64), rounded.astype(np.int64) + size)
    result = result * multiplier

    return np.where(exact, c.astype(np.int64), result)


def cube_coords_batch(positions: np.ndarray, size: int) -> np.ndarray:
    """[N, 3] float64 positions → [N, 3] int64 cube labels."""
    pos = np.asarray(positions, dtype=np.float64)
    return coord_clamp_batch(pos, size)


def clamp_region_coord_batch(coords: np.ndarray, region_size: int) -> np.ndarray:
    """Vectorized ``clamp_region_coord`` → int64 array."""
    c = np.asarray(coords, dtype=np.float64)

    def _positive(v: np.ndarray) -> np.ndarray:
        vi = v.astype(np.int64)  # truncation toward zero for v >= 0
        return vi - vi % np.int64(region_size)

    pos_result = _positive(np.maximum(c, 0.0))
    neg_result = -_positive(-c + float(region_size))

    result = np.where(c >= 0.0, pos_result, neg_result)
    return np.where(c == 0.0, np.int64(0), result)


def region_coords_batch(
    positions: np.ndarray, sx: int, sy: int, sz: int
) -> np.ndarray:
    """[N, 3] float64 positions → [N, 3] int64 region labels."""
    pos = np.asarray(positions, dtype=np.float64)
    out = np.empty(pos.shape, dtype=np.int64)
    out[..., 0] = clamp_region_coord_batch(pos[..., 0], sx)
    out[..., 1] = clamp_region_coord_batch(pos[..., 1], sy)
    out[..., 2] = clamp_region_coord_batch(pos[..., 2], sz)
    return out


# endregion
