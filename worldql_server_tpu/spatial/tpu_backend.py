"""TPU-accelerated :class:`SpatialBackend`: batched fan-out on device.

The reference resolves each LocalMessage with a per-message HashMap
probe + O(all-connected-peers) scan under a global write lock
(local_message.rs:63-86, peer_map.rs:151-163). Here the entire tick's
worth of queries resolves as ONE jitted device batch over a
device-resident subscription index — the north-star design from
BASELINE.json.

Layout (SoA, device-resident, integers only — no f64 on device):

* ``sub_key``   [S] int64 — spatial hash of (world, cube), sorted
* ``sub_world`` [S] int32 — interned world id, in key order
* ``sub_xyz``   [S, 3] int64 — exact cube coords, for hash verification
* ``sub_peer``  [S] int32 — interned peer id, in key order

A query is two binary searches (``searchsorted`` left/right) giving the
contiguous run of subscribers of its cube, an exactness check of
(world, cube) against the candidate row, a fixed-degree-K gather of
peer ids, and a replication mask — all fused by XLA into one kernel
launch for the whole batch. K is the max cube occupancy, rounded to a
power of two; S and M are padded to power-of-two capacity tiers so the
number of compiled shapes stays logarithmic.

The host keeps the authoritative dict index (inherited from
``CpuSpatialBackend``) — point queries and membership checks stay exact
and O(1) on host; ``flush()`` mirrors it to the device after mutations.
Quantization always runs host-side in numpy f64 (golden semantics,
cube_area.rs:23-44); the device only ever compares integer labels, so
TPU fast-math cannot perturb grid assignment.
"""

from __future__ import annotations

import uuid as uuid_mod
from functools import partial
from typing import Sequence

import numpy as np

from . import jaxconf  # noqa: F401  (must precede jax import)
import jax
import jax.numpy as jnp

from ..protocol.types import Replication, Vector3
from .backend import Cube, LocalQuery, to_cube
from .cpu_backend import CpuSpatialBackend
from .hashing import NO_WORLD, PAD_KEY, next_pow2, pad_to, spatial_keys
from .quantize import cube_coords_batch

_REPL_EXCEPT = np.int8(int(Replication.EXCEPT_SELF))
_REPL_ONLY = np.int8(int(Replication.ONLY_SELF))


def match_core(
    sub_key, sub_world, sub_xyz, sub_peer,
    q_key, q_world, q_xyz, q_sender, q_repl,
    *, k: int,
):
    """[M] queries × [S] sorted subscriptions → [M, K] peer ids (-1 pad).

    Pure traceable core; the single-chip backend jits it directly and
    the sharded backend (parallel/sharded_backend.py) wraps it in
    shard_map over a device mesh.
    """
    s = sub_key.shape[0]
    lo = jnp.searchsorted(sub_key, q_key, side="left")
    hi = jnp.searchsorted(sub_key, q_key, side="right")
    li = jnp.minimum(lo, s - 1)

    # Exactness: the hash located a candidate run; admit it only if the
    # run's first row carries the query's exact (world, cube).
    found = (
        (sub_key[li] == q_key)
        & (sub_world[li] == q_world)
        & jnp.all(sub_xyz[li] == q_xyz, axis=-1)
    )
    cnt = jnp.where(found, hi - lo, 0)

    offs = jnp.arange(k, dtype=lo.dtype)
    gidx = jnp.minimum(lo[:, None] + offs[None, :], s - 1)
    tgt = sub_peer[gidx]
    valid = offs[None, :] < cnt[:, None]

    # Replication filter (local_message.rs:60-86).
    is_sender = tgt == q_sender[:, None]
    repl = q_repl[:, None]
    valid &= jnp.where(
        repl == int(_REPL_EXCEPT),
        ~is_sender,
        jnp.where(repl == int(_REPL_ONLY), is_sender, True),
    )
    return jnp.where(valid, tgt, -1)


_match_kernel = partial(jax.jit, static_argnames=("k",))(match_core)


def match_core_sparse(
    sub_key, sub_world, sub_xyz, sub_peer,
    q_key, q_world, q_xyz, q_sender, q_repl,
    *, k: int, c: int,
):
    """Sparse variant: most queries resolve to an empty fan-out (an
    entity alone in its cube broadcasting except-self), so compact the
    non-empty rows on device and ship only those. Returns
    ``(rows[c], targets[c, k], n_hits)``: query indices with >= 1
    target, their target rows, and the true hit count (host re-fetches
    dense on the rare ``n_hits > c`` overflow). Cuts device→host result
    bytes by the hit rate — the dominant cost on PCIe, decisive on
    tunneled devices."""
    tgt = match_core(
        sub_key, sub_world, sub_xyz, sub_peer,
        q_key, q_world, q_xyz, q_sender, q_repl, k=k,
    )
    nz = jnp.any(tgt >= 0, axis=1)
    order = jnp.argsort(~nz, stable=True)  # hit rows first, in order
    rows = order[:c]
    return rows.astype(jnp.int32), tgt[rows], nz.sum(dtype=jnp.int32)


_match_kernel_sparse = partial(jax.jit, static_argnames=("k", "c"))(
    match_core_sparse
)


def match_core_csr(
    sub_key, sub_world, sub_xyz, sub_peer,
    q_key, q_world, q_xyz, q_sender, q_repl,
    *, k: int, t_cap: int,
):
    """CSR-compacted variant: returns ``(counts[M], flat[t_cap],
    total)`` — per-query fan-out counts and all target peer ids
    concatenated in query order. This is the layout the host needs to
    build per-peer frames, and it shrinks the device→host result from
    M×K to ~total ints (the dominant cost on the wire back). On
    ``total > t_cap`` overflow the tail is dropped; callers detect via
    ``total`` and re-fetch dense."""
    tgt = match_core(
        sub_key, sub_world, sub_xyz, sub_peer,
        q_key, q_world, q_xyz, q_sender, q_repl, k=k,
    )
    valid = tgt >= 0
    cnt = valid.sum(axis=1, dtype=jnp.int32)
    starts = jnp.cumsum(cnt) - cnt  # exclusive prefix
    slot = jnp.cumsum(valid, axis=1) - 1
    flat_idx = jnp.where(valid, starts[:, None] + slot, t_cap)
    flat_idx = jnp.minimum(flat_idx, t_cap)  # overflow tail → spill slot
    flat = jnp.full(t_cap + 1, -1, dtype=jnp.int32).at[flat_idx].max(
        jnp.where(valid, tgt, -1)
    )
    return cnt, flat[:t_cap], cnt.sum(dtype=jnp.int32)


_match_kernel_csr = partial(jax.jit, static_argnames=("k", "t_cap"))(
    match_core_csr
)


class TpuSpatialBackend(CpuSpatialBackend):
    """Device-batched backend. Mutations and point queries run on the
    host authority; ``match_local_batch`` runs on device."""

    def __init__(self, cube_size: int):
        super().__init__(cube_size)
        self._world_ids: dict[str, int] = {}
        self._peer_ids: dict[uuid_mod.UUID, int] = {}
        self._peer_list: list[uuid_mod.UUID] = []
        self._dirty = True
        self._seed = 0
        self._k = 8
        self._n_subs = 0
        self._dev: tuple | None = None  # (sub_key, sub_world, sub_xyz, sub_peer)

    # region: interning

    def _world_id(self, world: str) -> int:
        wid = self._world_ids.get(world)
        if wid is None:
            wid = self._world_ids[world] = len(self._world_ids)
        return wid

    def _peer_id(self, peer: uuid_mod.UUID) -> int:
        pid = self._peer_ids.get(peer)
        if pid is None:
            pid = self._peer_ids[peer] = len(self._peer_list)
            self._peer_list.append(peer)
        return pid

    # endregion

    # region: mutations (host authority + dirty mark)

    def add_subscription(
        self, world: str, peer: uuid_mod.UUID, pos: Vector3 | Cube
    ) -> bool:
        added = super().add_subscription(world, peer, pos)
        if added:
            self._world_id(world)
            self._peer_id(peer)
            self._dirty = True
        return added

    def remove_subscription(
        self, world: str, peer: uuid_mod.UUID, pos: Vector3 | Cube
    ) -> bool:
        removed = super().remove_subscription(world, peer, pos)
        if removed:
            self._dirty = True
        return removed

    def remove_peer(self, peer: uuid_mod.UUID) -> bool:
        removed = super().remove_peer(peer)
        if removed:
            self._dirty = True
        return removed

    # endregion

    # region: device mirror

    def _build_sorted(self):
        """Gather the host authority into key-sorted numpy SoA arrays:
        → (keys, worlds, xyz, peers, max_cube_occupancy), or None if
        empty. Also advances the hash seed past any collision."""
        n = self.subscription_count()
        self._n_subs = n
        if n == 0:
            return None

        worlds = np.empty(n, dtype=np.int32)
        xyz = np.empty((n, 3), dtype=np.int64)
        peers = np.empty(n, dtype=np.int32)
        n_cubes = 0
        i = 0
        for wname, w in self._worlds.items():
            wid = self._world_ids[wname]
            n_cubes += len(w.cubes)
            for cube, cube_peers in w.cubes.items():
                j = i + len(cube_peers)
                worlds[i:j] = wid
                xyz[i:j] = cube
                peers[i:j] = [self._peer_ids[p] for p in cube_peers]
                i = j
        assert i == n

        # Seed search: distinct cubes must map to distinct keys, and no
        # real key may equal the padding sentinel (see spatial/hashing).
        while True:
            keys = spatial_keys(worlds, xyz, self._seed)
            uniq, counts = np.unique(keys, return_counts=True)
            cube_occupancy = int(counts.max())
            if uniq.size == n_cubes and (uniq[-1] if uniq.size else 0) != PAD_KEY:
                break
            self._seed += 1

        order = np.argsort(keys, kind="stable")
        return keys[order], worlds[order], xyz[order], peers[order], cube_occupancy

    def flush(self) -> None:
        """Rebuild the device mirror from the host authority."""
        if not self._dirty:
            return
        self._dirty = False

        built = self._build_sorted()
        if built is None:
            self._dev = None
            return
        keys, worlds, xyz, peers, cube_occupancy = built

        self._k = next_pow2(cube_occupancy, 8)
        cap = next_pow2(len(keys))
        self._dev = (
            jnp.asarray(pad_to(keys, cap, PAD_KEY)),
            jnp.asarray(pad_to(worlds, cap, NO_WORLD)),
            jnp.asarray(pad_to(xyz, cap, np.int64(-(2**62)))),
            jnp.asarray(pad_to(peers, cap, np.int32(-1))),
        )

    # endregion

    # region: batched hot path

    def match_arrays(
        self,
        world_ids: np.ndarray,
        positions: np.ndarray,
        sender_ids: np.ndarray,
        repls: np.ndarray,
    ) -> np.ndarray:
        """Array-native hot path: [M] int32 interned world ids, [M, 3]
        f64 positions, [M] int32 sender peer ids (-1 for none), [M] int8
        replication → [M, K] int32 peer ids, -1-padded.

        Quantizes host-side (golden f64 semantics), then one fused
        device batch. The object API wraps this; benchmarks call it
        directly.
        """
        m, result = self.match_arrays_async(
            world_ids, positions, sender_ids, repls
        )
        if result is None:
            return np.full((m, 1), -1, dtype=np.int32)
        # Convert the whole (prefetched) array, trim on host — a device
        # slice would dispatch again and re-transfer.
        return np.asarray(result)[:m]

    def match_arrays_async(
        self,
        world_ids: np.ndarray,
        positions: np.ndarray,
        sender_ids: np.ndarray,
        repls: np.ndarray,
        max_hits: int | None = None,
        csr_cap: int | None = None,
    ):
        """Asynchronous hot path: dispatch without forcing the result.

        Returns ``(m, result)`` where ``result`` is the device value —
        dense ``targets``; with ``max_hits`` the sparse
        ``(rows, targets, n_hits)`` triple; with ``csr_cap`` the
        compacted ``(counts, flat_targets, total)`` triple. Callers
        overlap ticks by dispatching tick t+1 before reading tick t
        (double buffering: transfer and compute of adjacent ticks
        overlap)."""
        self.flush()
        m = len(world_ids)
        if self._dev is None or m == 0:
            return m, None

        cubes = cube_coords_batch(positions, self.cube_size)
        keys = spatial_keys(world_ids, cubes, self._seed)

        cap = self._query_cap(m)
        queries = (
            pad_to(keys, cap, PAD_KEY),
            pad_to(world_ids, cap, NO_WORLD),
            pad_to(cubes, cap, np.int64(0)),
            pad_to(sender_ids.astype(np.int32), cap, np.int32(-1)),
            pad_to(repls.astype(np.int8), cap, np.int8(0)),
        )
        if csr_cap is not None:
            result = self._dispatch_csr(queries, next_pow2(csr_cap))
        elif max_hits is not None:
            result = self._dispatch_sparse(queries, next_pow2(max_hits))
        else:
            result = (self._dispatch(queries),)
        # Enqueue D2H now: by the time a pipelined caller reads the
        # result, the copy has landed — the read costs no round-trip.
        for r in result:
            copy = getattr(r, "copy_to_host_async", None)
            if copy is not None:
                copy()
        return m, result[0] if max_hits is None and csr_cap is None else result

    def _query_cap(self, m: int) -> int:
        """Padded query-batch capacity tier; sharded backends round to
        their batch-axis divisibility."""
        return next_pow2(m)

    def _dispatch(self, queries: tuple):
        """Run the padded query arrays against the device mirror. Numpy
        args go straight into the jitted call so all five H2D transfers
        ride one dispatch — on tunneled/remote devices per-array
        ``device_put`` round-trips dominate otherwise."""
        return _match_kernel(*self._dev, *queries, k=self._k)

    def _dispatch_sparse(self, queries: tuple, c: int):
        return _match_kernel_sparse(*self._dev, *queries, k=self._k, c=c)

    def _dispatch_csr(self, queries: tuple, t_cap: int):
        return _match_kernel_csr(*self._dev, *queries, k=self._k, t_cap=t_cap)

    def match_local_batch(
        self, queries: Sequence[LocalQuery]
    ) -> list[list[uuid_mod.UUID]]:
        return self.collect_local_batch(self.dispatch_local_batch(queries))

    def dispatch_local_batch(self, queries: Sequence[LocalQuery]):
        """Encode + launch a query batch without waiting for results.

        Runs on the owning (event-loop) thread — it reads the interning
        dicts, which mutate there. The returned handle goes to
        ``collect_local_batch``, which only blocks on the device and may
        safely run on a worker thread (tick batcher overlap).
        """
        m = len(queries)
        if m == 0:
            return (0, None)
        world_ids = np.fromiter(
            (self._world_ids.get(q.world, -1) for q in queries),
            dtype=np.int32, count=m,
        )
        positions = np.empty((m, 3), dtype=np.float64)
        for i, q in enumerate(queries):
            positions[i] = (q.position.x, q.position.y, q.position.z)
        sender_ids = np.fromiter(
            (self._peer_ids.get(q.sender, -1) for q in queries),
            dtype=np.int32, count=m,
        )
        repls = np.fromiter(
            (int(q.replication) for q in queries), dtype=np.int8, count=m
        )
        return self.match_arrays_async(world_ids, positions, sender_ids, repls)

    def collect_local_batch(self, handle) -> list[list[uuid_mod.UUID]]:
        """Wait for a dispatched batch and decode fan-out UUID lists.
        Thread-safe against concurrent interning: peer ids are
        append-only, so index reads stay valid."""
        m, result = handle
        if result is None:
            return [[] for _ in range(m)]
        tgt = np.asarray(result)[:m]

        mask = tgt >= 0
        counts = mask.sum(axis=1)
        flat = tgt[mask]
        peer_list = self._peer_list
        out: list[list[uuid_mod.UUID]] = []
        pos = 0
        for c in counts:
            out.append([peer_list[i] for i in flat[pos:pos + c]])
            pos += c
        return out

    # endregion

    # region: introspection

    def device_stats(self) -> dict:
        return {
            "subscriptions": self._n_subs,
            "capacity": 0 if self._dev is None else int(self._dev[0].shape[0]),
            "max_fanout_k": self._k,
            "worlds": len(self._world_ids),
            "peers": len(self._peer_list),
            "hash_seed": self._seed,
            "dirty": self._dirty,
        }

    # endregion
