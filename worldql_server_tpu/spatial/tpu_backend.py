"""TPU-accelerated :class:`SpatialBackend`: batched fan-out on device.

The reference resolves each LocalMessage with a per-message HashMap
probe + O(all-connected-peers) scan under a global write lock
(local_message.rs:63-86, peer_map.rs:151-163). Here the entire tick's
worth of queries resolves as ONE jitted device batch over a
device-resident subscription index — the north-star design from
BASELINE.json.

Index layout — two segments, LSM-style, so a mutation costs O(log S)
instead of an O(S) rebuild (the reference's AreaMap does O(1) dict
updates, area_map.rs:72-85; this is the static-shape analog):

* **base**: large sorted-by-key SoA. On device each row is 20 bytes —
  ``key i64 | key2 i64 | peer i32`` — where ``key2`` is a second,
  independent hash standing in for the raw (world, cube) identity
  (hashing.py: combined collision odds ~2⁻¹²⁸); the host keeps the
  exact ``world``/``cube`` columns as authority. Immutable except for
  *tombstones*: a removal sets ``peer = -1`` (host + one device
  scatter per flush). Keys never change, so the binary-search run
  structure and the first-row exactness probe stay valid; dead rows
  gather as ``-1`` targets, which every consumer already filters.
* **delta**: small insertion-ordered append log holding rows added
  since the last compaction. Each flush sorts the *live* delta rows
  (O(D log D), D = churn since compaction) and uploads them as a
  second device segment; a query matches both segments and
  concatenates the target lists.

**Compaction** folds base+delta into a fresh sorted base. It runs on a
background thread against a snapshot while the serving index keeps
answering (and mutating); removals that touch snapshot rows are logged
as (key, peer) pairs and replayed against the new base at swap time,
so the swap itself is O(replay) on the owning thread.

A query resolves its cube's contiguous subscriber run per segment via
ONE packed bucket-probe row gather (probe_tables; binary search is the
per-segment fallback), verifies exactness against the second key
family, and the batch's CSR result assembles straight from those run
windows (match_run_csr) — row gathers and index scans only, no data
scatter, no per-query gather-degree bound. The dense [M, K] path
(match_core; K = max cube occupancy, power-of-two) remains for the
overflow fallback and parity tests. Segment and query capacities are
power-of-two tiers so the number of compiled shapes stays logarithmic.

Quantization always runs host-side in numpy f64 (golden semantics,
cube_area.rs:23-44); the device only ever compares integer labels, so
TPU fast-math cannot perturb grid assignment.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid as uuid_mod
from collections import Counter
from functools import partial
from typing import Sequence

import numpy as np

from . import jaxconf  # noqa: F401  (must precede jax import)
import jax
import jax.numpy as jnp

from ..protocol.types import Replication, Vector3
from ..queries.kinds import PARAM_LANES as _QUERY_PARAM_LANES
from ..utils import retrace
from .backend import Cube, LocalQuery, SpatialBackend, to_cube
from .delta_ticks import TemporalCoherence, row_signatures
from .hashing import (
    MIX_M1, MIX_M2, NO_WORLD, PAD_KEY, n_distinct, next_pow2, pad_to,
    spatial_keys, spatial_keys2,
)
from .native_keys import encode_queries, query_keys

_log = logging.getLogger(__name__)

_REPL_EXCEPT = np.int8(int(Replication.EXCEPT_SELF))
_REPL_ONLY = np.int8(int(Replication.ONLY_SELF))

_XYZ_PAD = np.int64(-(2 ** 62))


# --------------------------------------------------------------------
# Device kernels
# --------------------------------------------------------------------

#: slots per probe-table bucket — one bucket row is one aligned row
#: gather, and row-gather cost is pure BYTES on v5e (an [M, 16] i32 row
#: gather costs ~half an [M, 16] i64 one, measured)
PROBE_E = 8
#: bucket-count ceiling: at the cap the packed table is
#: 2^21 × 16 lanes × 4 B = 128 MB and the load factor at ~630K distinct
#: cubes is ~0.3 cubes/bucket — bucket overflow is ~impossible, and
#: correctness never depends on the table fitting (oflow routes the
#: segment to binary search)
PROBE_MAX_BUCKETS = 1 << 21
#: seed folding the bucket hash away from both key hash families.
#: np.uint64, NOT jnp: a module-level jnp scalar executes a device
#: computation at import time, which breaks jax.distributed.initialize
#: ("must be called before any JAX computations") for every process
#: that imports the backend before joining the runtime — the exact
#: boot order of a multi-host server (parallel/mesh.py).
_PROBE_SEED = np.uint64(0xA0761D6478BD642F)

SEG_ARRAYS = 6  # (key, key2, peer, run_rem, tbl, oflow)


def probe_buckets_for(n_cubes: int) -> int:
    """Bucket-count tier for a segment with ``n_cubes`` distinct cubes:
    2x headroom (load factor <= 0.5) against PROBE_E-slot buckets makes
    bucket overflow ~never (Poisson tail at λ<=0.5, e=8), and any
    overflowing or tag-colliding build falls back to binary search for
    the whole segment (oflow) — slower, never wrong."""
    return min(next_pow2(2 * max(n_cubes, 8)), PROBE_MAX_BUCKETS)


def _bucket_hash(keys, seed=_PROBE_SEED):
    """[..] i64 keys → uint64 bucket hashes (splitmix64, distinct seed
    from both key families). Device-only: build and probe both run on
    device, so no host twin has to stay bit-identical."""
    x = keys.view(jnp.uint64) ^ seed
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(MIX_M1)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(MIX_M2)
    return x ^ (x >> jnp.uint64(31))


def probe_tables(sorted_keys, sorted_keys2, *, n_buckets: int):
    """Build the single-level PACKED bucket probe table for a sorted
    segment on device.

    ``tbl`` is [B, 3E] i32: each bucket row holds E first-key TAGS
    (top-32 bits; pad 0), E second-family verify tags (top-32 bits of
    key2), and E run-start indices into the sorted segment (pad -1).
    A query resolves its run with ONE [M, 3E] i32 row gather plus two
    element gathers ([M] i32 run remainder, [M] i64 key2 backstop) —
    the second-family TAG rides the row to reject almost every
    collision cheaply, and the run-start row's full key2 settles the
    rest.

    Exactness contract: a probe hit proves bucket (log2 B bits of an
    independent mix of key1) + key1 tag (32 bits) agreement to pick
    the lane, then FULL 64-bit key2 equality at the run-start row
    (_probe_run_bounds) — the same exact-match contract as the
    binary-search fallback, so a cross-cube tag1+tag2 double collision
    can no longer mis-route silently (ADVICE r5; both families are
    already hashes of the same (world, cube), hashing.py). A cube
    whose (bucket, key1-tag) collides with a DIFFERENT cube — the case
    where the row alone could pick the wrong lane — is detected here
    at build time and routes the segment to the binary-search fallback
    via ``oflow``, exactly like bucket overflow: slower, never wrong.

    Returns ``(tbl [B, 3E] i32, oflow [1] i32)`` — ``oflow[0]`` counts
    cubes that overflowed their bucket's E slots or tag-collided
    in-bucket (~never at load factor <= 0.5).

    Cost: one [S] i64 argsort + three scatters — amortized into the
    flush / compaction launch that sorted the segment anyway.
    """
    s = sorted_keys.shape[0]
    e = PROBE_E
    idx = jnp.arange(s, dtype=jnp.int32)
    first = jnp.concatenate([
        jnp.ones((1,), bool), sorted_keys[1:] != sorted_keys[:-1]
    ]) & (sorted_keys != PAD_KEY)

    b = (_bucket_hash(sorted_keys) & jnp.uint64(n_buckets - 1)).astype(
        jnp.int64
    )
    tag = (sorted_keys >> jnp.int64(32)).astype(jnp.int32)
    tag2 = (sorted_keys2 >> jnp.int64(32)).astype(jnp.int32)
    # order run starts by (bucket, tag): bucket runs give slot ranks,
    # and duplicate (bucket, tag) pairs land adjacent for detection
    sentinel = jnp.int64(1) << jnp.int64(62)
    comp = jnp.where(
        first,
        (b << jnp.int64(32))
        | (tag.astype(jnp.int64) & jnp.int64(0xFFFFFFFF)),
        sentinel,
    )
    order = jnp.argsort(comp, stable=True).astype(jnp.int32)
    sc = comp[order]
    member = sc < sentinel
    dup = jnp.concatenate([
        jnp.zeros((1,), bool), sc[1:] == sc[:-1]
    ]) & member
    sb = (sc >> jnp.int64(32)).astype(jnp.int32)
    bstart = jnp.concatenate([jnp.ones((1,), bool), sb[1:] != sb[:-1]])
    rank = idx - jax.lax.cummax(jnp.where(bstart, idx, 0))
    fit = member & (rank < e) & ~dup
    oflow = (member & ~fit).sum(dtype=jnp.int32)[None]

    # skipped lanes get DISTINCT out-of-bounds slots, keeping the
    # unique_indices promise honest (mode="drop" ignores them)
    total = n_buckets * 3 * e
    row0 = sb * (3 * e)
    tag_slot = jnp.where(fit, row0 + rank, total + idx)
    tag2_slot = jnp.where(fit, row0 + e + rank, total + s + idx)
    lo_slot = jnp.where(fit, row0 + 2 * e + rank, total + 2 * s + idx)
    # init pattern per bucket: E+E tag lanes of 0, E lo lanes of -1 —
    # a pad-tag false hit carries lo -1 and can never win the
    # per-query max in _probe_run_bounds
    init = jnp.tile(
        jnp.concatenate([
            jnp.zeros(2 * e, jnp.int32), jnp.full(e, -1, jnp.int32)
        ]),
        n_buckets,
    )
    tbl = (
        init
        .at[tag_slot].set(tag[order], mode="drop", unique_indices=True)
        .at[tag2_slot].set(tag2[order], mode="drop", unique_indices=True)
        .at[lo_slot].set(order, mode="drop", unique_indices=True)
    )
    return tbl.reshape(n_buckets, 3 * e), oflow


def _probe_run_bounds(tbl, sub_key2, sub_rem, q_key, q_key2):
    """Per-query (run start, run length) via ONE packed bucket-row
    gather + two element gathers (run remainder, key2 backstop). See
    probe_tables for the exactness contract."""
    s = sub_rem.shape[0]
    nb = tbl.shape[0]
    e = tbl.shape[1] // 3
    b = (_bucket_hash(q_key) & jnp.uint64(nb - 1)).astype(jnp.int32)
    rows = jnp.take(tbl, b, axis=0)     # [M, 3E] i32 — one row gather
    q_tag = (q_key >> jnp.int64(32)).astype(jnp.int32)
    q_tag2 = (q_key2 >> jnp.int64(32)).astype(jnp.int32)
    # <= 1 real lane can match on the key1 tag (build rejects in-bucket
    # dups); the key2 tag rides the same row as the verify family. Pad
    # lanes carry lo -1 and lose the max to any real run start.
    hit = (rows[:, :e] == q_tag[:, None]) \
        & (rows[:, e:2 * e] == q_tag2[:, None])
    lo = jnp.where(hit, rows[:, 2 * e:], jnp.int32(-1)).max(axis=1)
    li = jnp.clip(lo, 0, s - 1)
    # True-equality backstop (ADVICE r5): one [M] i64 element gather
    # verifies the FULL key2 at the run-start row, closing the
    # cross-cube tag1+tag2 double-collision hole the 32+32-bit row
    # tags leave open — the probe branch now enforces the same exact-
    # match contract as the binary-search fallback.
    found = (lo >= 0) & (sub_key2[li] == q_key2)
    return li, jnp.where(found, sub_rem[li], 0)


def _seg_run_bounds(seg, q_key, q_key2):
    """Run bounds for one 6-array segment: packed bucket probe when the
    table built cleanly (almost always), binary search when any cube
    overflowed or tag-collided (oflow[0] > 0). The branch scalar lives
    on device — no host sync decides it."""
    sub_key, sub_key2, _, sub_rem, tbl, oflow = seg
    return jax.lax.cond(
        oflow[0] > 0,
        lambda: _run_bounds(sub_key, sub_key2, sub_rem, q_key, q_key2),
        lambda: _probe_run_bounds(tbl, sub_key2, sub_rem, q_key, q_key2),
    )


def match_core(seg, q_key, q_key2, q_sender, q_repl, *, k: int):
    """[M] queries × one 7-array segment → [M, K] peer ids (-1 pad).

    Pure traceable core; the single-chip backend jits it (per segment)
    and the sharded backend (parallel/sharded_backend.py) wraps it in
    shard_map over a device mesh. Tombstoned rows carry ``peer == -1``
    and fall out through the same mask that drops replication-filtered
    rows.
    """
    lo, cnt = _seg_run_bounds(seg, q_key, q_key2)
    return _gather_filtered(seg[2], lo, cnt, q_sender, q_repl, k=k)


def _run_bounds(sub_key, sub_key2, sub_rem, q_key, q_key2):
    """Per-query (run start, run length) in a sorted segment.

    One binary search (``side='left'``) instead of two: the segment
    carries a precomputed per-row run-remainder column (``sub_rem[r]``
    = rows from r to the end of r's equal-key run), so the run length
    at ``lo`` is a single [M] gather — half the search cost, which is
    the kernel's dominant term. Runs never change between compactions
    (tombstones rewrite peers, not keys), so the column stays valid
    for a segment's lifetime.

    Exactness: the hash locates a candidate run; it counts only if the
    run's first row also matches under the second, independent key
    family (spatial/hashing.py: ~2^-128 combined collision odds —
    16 key bytes replace the 28-byte raw (world, cube) identity on
    the wire and in the index rows)."""
    s = sub_key.shape[0]
    lo = jnp.searchsorted(sub_key, q_key, side="left")
    li = jnp.minimum(lo, s - 1)
    found = (sub_key[li] == q_key) & (sub_key2[li] == q_key2)
    return lo, jnp.where(found, sub_rem[li], 0)


def run_remainders(sorted_keys):
    """[S] i32 column: rows from each row to the end of its equal-key
    run (inclusive). Pure vectorized segment scan — no gathers."""
    s = sorted_keys.shape[0]
    idx = jnp.arange(s, dtype=jnp.int32)
    last = jnp.concatenate([
        sorted_keys[1:] != sorted_keys[:-1],
        jnp.ones((1,), bool),
    ])
    # exclusive end of each row's run = index of its run's last row + 1,
    # found by a reverse running-minimum over last-row positions
    ends = jax.lax.cummin(
        jnp.where(last, idx, jnp.int32(s - 1)), reverse=True
    )
    return ends + 1 - idx


def _window_gather(arr, lo, k):
    """[M] window starts → [M, k] contiguous windows of a 1-D array
    (length a multiple of 8), via aligned row gathers from an [S/8, 8]
    view + an 8-way static-rotation select. A TPU element gather costs
    ~8 ns/element; an aligned row gather ~25x less (measured on v5e) —
    the windows here are contiguous, so only the alignment varies.
    Lanes past the array end read the clamped last row; every caller
    masks them (they can only be lanes beyond the run length)."""
    s = arr.shape[0]
    if s < 8 or s % 8:
        idx = jnp.minimum(lo[:, None] + jnp.arange(k, dtype=lo.dtype), s - 1)
        return arr[idx]
    nrows = s // 8
    v = arr.reshape(nrows, 8)
    r = jnp.minimum(lo >> 3, nrows - 1).astype(jnp.int32)
    c = (lo & 7).astype(jnp.int32)
    rows = jnp.concatenate(
        [jnp.take(v, jnp.minimum(r + t, nrows - 1), axis=0)
         for t in range((k + 7) // 8 + 1)], axis=1)
    out = rows[:, 0:k]
    for cc in range(1, 8):
        out = jnp.where((c == cc)[:, None], rows[:, cc:cc + k], out)
    return out


def _gather_filtered(sub_peer, lo, cnt, q_sender, q_repl, *, k):
    """Gather up to ``k`` targets per run and apply the tombstone +
    replication filters (local_message.rs:60-86)."""
    offs = jnp.arange(k, dtype=lo.dtype)
    tgt = _window_gather(sub_peer, lo, k)
    valid = (offs[None, :] < cnt[:, None]) & (tgt >= 0)
    is_sender = tgt == q_sender[:, None]
    repl = q_repl[:, None]
    valid &= jnp.where(
        repl == int(_REPL_EXCEPT),
        ~is_sender,
        jnp.where(repl == int(_REPL_ONLY), is_sender, True),
    )
    return jnp.where(valid, tgt, -1)


def _multi_match(flat_args, ks):
    """Match against ``len(ks)`` segments, concatenating the per-query
    target lists along the K axis. ``flat_args`` is SEG_ARRAYS arrays
    per segment followed by the 4 query arrays."""
    nseg = len(ks)
    na = SEG_ARRAYS
    queries = flat_args[na * nseg:]
    parts = [
        match_core(flat_args[na * i:na * i + na], *queries, k=ks[i])
        for i in range(nseg)
    ]
    return parts[0] if nseg == 1 else jnp.concatenate(parts, axis=1)


def compact_sparse(tgt, *, c: int):
    """Sparse compaction of a dense [M, K] target table: most queries
    resolve to an empty fan-out (an entity alone in its cube
    broadcasting except-self), so compact the non-empty rows on device
    and ship only those. Returns ``(rows[c], targets[c, k], n_hits)``:
    query indices with >= 1 target, their target rows, and the true hit
    count (host re-fetches dense on the rare ``n_hits > c`` overflow).
    Cuts device→host result bytes by the hit rate — the dominant cost
    on PCIe, decisive on tunneled devices."""
    nz = jnp.any(tgt >= 0, axis=1)
    order = jnp.argsort(~nz, stable=True)  # hit rows first, in order
    rows = order[:c]
    return rows.astype(jnp.int32), tgt[rows], nz.sum(dtype=jnp.int32)


#: CSR zone-A row width: one identity row of this many lanes per query
CSR_ROW = 8
#: CSR zone-B row width: hot-remainder regions pad to multiples of
#: this. Wider rows amortize zone B's per-row metadata gather (the
#: dominant Zipf-crowd cost — hot regions average hundreds of lanes)
#: over 4x more output lanes at <= 31 pad slots per hot region.
CSR_ROW_B = 32
#: zone-B assembly block size (rows per lax.map chunk): a FIXED block
#: shape pins XLA to one gather codegen for every batch size — the
#: straight-line form scalarized at ~2M output rows (55 vs ~20 ns/row).
_ZONE_B_CHUNK = 1 << 17
#: tail-tier block size: the remainder past the full 2^17 chunks maps
#: in these, bounding discarded padding rows below one tail block.
_ZONE_B_TAIL_CHUNK = 1 << 14


def run_bounds_all(segs, queries):
    """Per-segment (run start, RAW run length) for every query."""
    q_key, q_key2 = queries[0], queries[1]
    los, cnts = [], []
    for seg in segs:
        lo, cnt = _seg_run_bounds(seg, q_key, q_key2)
        los.append(lo)
        cnts.append(cnt)
    return los, cnts


def csr_layout(cnts, rows_cap, row_lanes=CSR_ROW_B):
    """The row-padded zone-B layout from raw per-segment lengths:
    query q's segment-s region occupies ``ceil(cnt / row_lanes)``
    rows of ``row_lanes`` lanes at ``row_start[q, s]`` (q-major,
    segment-minor). Returns ``(counts [M, nseg], row_start [M*nseg],
    owner [rows_cap], total_rows)`` where ``owner[j]`` is the
    flattened (q, s) slot that output row j belongs to — pure scans
    plus ONE tiny index scatter, no data movement."""
    counts = jnp.stack(cnts, axis=1)               # [M, nseg] raw
    prows = ((counts + (row_lanes - 1)) // row_lanes).reshape(-1)
    row_start = jnp.cumsum(prows) - prows          # [M*nseg]
    total_rows = prows.sum(dtype=jnp.int32)
    slot = jnp.arange(prows.shape[0], dtype=jnp.int32)
    mark = jnp.where(prows > 0, row_start, rows_cap + 1 + slot)
    owner = jax.lax.cummax(
        jnp.zeros(rows_cap, jnp.int32)
        .at[mark].max(slot, mode="drop")
    )
    return counts, row_start, owner, total_rows


def match_run_csr(flat_args, nseg, t_cap):
    """Fan-out CSR assembled STRAIGHT from the index's run windows.

    Every query's targets are one contiguous slice of a segment's
    sorted peer column, so the flat CSR result is a permutation of
    window reads: per output row, gather 8 lanes starting at
    ``run_start + 8 * block``. There is NO data scatter, no per-query
    gather degree K, and no two-tier overflow machinery — a 2-member
    cube and a 250-member Zipf crowd cost exactly their output size.
    (This replaced a two-tier k_lo/h_cap design whose tier-2 dense
    [hot, K] table and element scatters dominated the kernel: 71 ms →
    ~4 ms at 16K Zipf queries on v5e.)

    Layout/contract: ``counts [M, nseg]`` are RAW run lengths; query
    q's segment-s region spans ``ceil(counts[q, s]/8)*8`` slots
    (q-major, segment-minor), and within a region the device leaves
    ``-1`` holes where a lane was tombstoned or replication-filtered
    (local_message.rs:60-86) — consumers read ``counts[q, s]`` lanes
    and keep the ``>= 0`` ones. ``total`` is the raw lane total, or
    the impossible ``t_cap + 1`` when the padded layout overflows
    ``t_cap`` (caller retries bigger, same contract as before)."""
    na = SEG_ARRAYS
    segs = [tuple(flat_args[na * i:na * i + na]) for i in range(nseg)]
    queries = flat_args[na * nseg:]
    los, cnts = run_bounds_all(segs, queries)
    return run_csr_assemble(segs, los, cnts, cnts, queries, t_cap)


def _repl_mask(vals, sender_col, repl_col):
    """Replication filter lanes (local_message.rs:60-86)."""
    is_sender = vals == sender_col
    return jnp.where(
        repl_col == int(_REPL_EXCEPT),
        ~is_sender,
        jnp.where(repl_col == int(_REPL_ONLY), is_sender, True),
    )


def zone_b_cnts(cnts):
    """Zone-B raw lengths from per-segment raw lengths: every
    segment's first CSR row ships in a zone-A identity row, only the
    remainders past lane 8 owner-map into zone B."""
    return [jnp.maximum(c - CSR_ROW, 0) for c in cnts]


def run_csr_assemble(segs, los, cnts, cnts_local, queries, t_cap):
    """The assembly core of :func:`match_run_csr`. ``cnts`` are the
    GLOBAL raw run lengths defining the layout; ``cnts_local`` what
    THIS device's segment columns actually hold (single-chip: the
    same arrays; on a mesh each space shard passes its local counts,
    so only the run's owning shard contributes lanes and a pmax merge
    reassembles the flat result).

    Two zones (the cost split that makes both crowd regimes cheap):

    * **zone A** — one IDENTITY row per (query, segment): rows
      [0, M*nseg), query-major, holding the first ``min(cnt, 8)``
      lanes of that segment's run. No owner map, no per-row metadata
      gathers — one window gather per segment plus elementwise masks.
      Typical runs (uniform crowds, delta-segment churn) fit here
      entirely.
    * **zone B** — rows after zone A: owner-mapped CSR_ROW_B-lane
      rows for remainders past lane 8. Pays one aligned 8-lane
      metadata row gather per row, but only hot rows exist here —
      under a Zipf crowd this zone is ~the whole result and the wide
      rows amortize the metadata.
    """
    nseg = len(segs)
    q_sender, q_repl = queries[2], queries[3]
    m = q_sender.shape[0]
    rows_cap_b = (t_cap - m * CSR_ROW * nseg) // CSR_ROW_B
    assert rows_cap_b >= 1, "t_cap must cover the zone-A identity rows"
    counts = jnp.stack(cnts, axis=1)               # [M, nseg] raw

    # --- zone A: one identity row per (query, segment) ---
    offs8 = jnp.arange(CSR_ROW, dtype=jnp.int32)[None, :]
    zone_a_parts = []
    for s, seg in enumerate(segs):
        vals_a = _window_gather(seg[2], los[s], CSR_ROW)
        valid_a = (
            (offs8 < jnp.minimum(cnts[s], CSR_ROW)[:, None])
            & (cnts_local[s] > 0)[:, None]
            & (vals_a >= 0)
            & _repl_mask(vals_a, q_sender[:, None], q_repl[:, None])
        )
        zone_a_parts.append(jnp.where(valid_a, vals_a, -1))
    # interleave query-major: row q*nseg + s
    zone_a = (
        zone_a_parts[0] if nseg == 1
        else jnp.stack(zone_a_parts, axis=1).reshape(-1, CSR_ROW)
    )

    # --- zone B: owner-mapped hot rows (CSR_ROW_B lanes each) ---
    # All per-row metadata lives in ONE [M*nseg, 8] i32 table so a row
    # costs a single aligned 8-lane ROW gather — ~25x cheaper per
    # element than the element gathers it replaces (same cost model as
    # _window_gather; this was previously two packed-i64 element
    # gathers per row, the dominant zone-B cost on v5e).
    cnts_b = zone_b_cnts(cnts)
    # The assembly runs CHUNKED: a lax.map over fixed-size row blocks.
    # Straight-line assembly lets XLA pick a different gather codegen
    # per output shape, and at ~2M rows it scalarized to 55 ns/row
    # while 131K- and 8M-row shapes ran at ~23 ns/row; mapping the SAME
    # block shape regardless of total rows pins the good codegen —
    # measured flat 17-19.5 ns/row across 131K/2M/8M rows on v5e.
    # Two chunk tiers bound the dead padding work at < one TAIL chunk
    # (the tail would otherwise round up to a full 2^17 block — up to
    # 131K discarded rows) while compiling at most two body shapes.
    chunk = min(_ZONE_B_CHUNK, next_pow2(max(rows_cap_b, 1)))
    tail_chunk = min(_ZONE_B_TAIL_CHUNK, chunk)
    n_full = rows_cap_b // chunk
    n_tail = -(-(rows_cap_b - n_full * chunk) // tail_chunk)
    rows_pad = n_full * chunk + n_tail * tail_chunk
    _, row_start, owner, total_rows_b = csr_layout(
        cnts_b, rows_pad, CSR_ROW_B
    )

    def slotify(per_seg):
        return jnp.stack(per_seg, axis=1).reshape(-1)

    # every segment's first row lives in zone A
    los_eff = [lo + CSR_ROW for lo in los]
    own = [(cl > 0).astype(jnp.int32) for cl in cnts_local]
    meta8 = jnp.stack([
        slotify(los_eff),
        slotify(cnts_b),
        slotify(own),
        row_start,
        slotify([q_sender] * nseg),
        slotify([q_repl.astype(jnp.int32)] * nseg),
        jnp.zeros(m * nseg, jnp.int32),
        jnp.zeros(m * nseg, jnp.int32),
    ], axis=1)

    lane = jnp.arange(CSR_ROW_B, dtype=jnp.int32)[None, :]

    def make_chunk_fn(size):
        def zone_b_chunk(start):
            j = start + jnp.arange(size, dtype=jnp.int32)
            own_c = jax.lax.dynamic_slice_in_dim(owner, start, size)
            live_row = (j < total_rows_b)[:, None]
            m8 = jnp.take(meta8, own_c, axis=0)
            s_of = own_c - (own_c // nseg) * nseg
            lo_row = m8[:, 0]
            cnt_row = m8[:, 1]
            own_row = m8[:, 2] > 0
            rs = m8[:, 3]
            sender_row = m8[:, 4:5]
            repl_row = m8[:, 5:6]
            block = j - rs
            offs = block[:, None] * CSR_ROW_B + lane

            zb = jnp.full((size, CSR_ROW_B), -1, jnp.int32)
            for s, seg in enumerate(segs):
                src = lo_row + block * CSR_ROW_B
                vals = _window_gather(seg[2], src, CSR_ROW_B)
                valid = (
                    (offs < cnt_row[:, None])
                    & own_row[:, None]             # this shard owns it
                    & (vals >= 0)                  # tombstones
                    & (s_of == s)[:, None]
                    & live_row
                    & _repl_mask(vals, sender_row, repl_row)
                )
                zb = jnp.where(valid, vals, zb)
            return zb
        return zone_b_chunk

    zone_b_parts = []
    for size, n0, count in ((chunk, 0, n_full),
                            (tail_chunk, n_full * chunk, n_tail)):
        if count:
            starts = n0 + size * jnp.arange(count, dtype=jnp.int32)
            zone_b_parts.append(
                jax.lax.map(make_chunk_fn(size), starts)
                .reshape(count * size, CSR_ROW_B)
            )
    zone_b = jnp.concatenate(zone_b_parts)[:rows_cap_b]

    flat = jnp.concatenate([
        zone_a.reshape(-1),
        zone_b.reshape(-1),
        jnp.full(
            t_cap - m * CSR_ROW * nseg - rows_cap_b * CSR_ROW_B, -1,
            jnp.int32,
        ),
    ])
    total = counts.sum(dtype=jnp.int32)
    total = jnp.where(total_rows_b > rows_cap_b, t_cap + 1, total)
    return counts, flat, total


@partial(jax.jit, static_argnames=("nseg", "t_cap"))
def _match_run_csr_kernel(*flat_args, nseg, t_cap):
    return match_run_csr(flat_args, nseg, t_cap)


def pack_csr(counts, flat, *, bucket: int):
    """Pack the zoned CSR flat result into a dense ``[bucket]`` lane
    array ON DEVICE, so the D2H fetch ships O(actual fan-out) bytes
    instead of the O(t_cap) capacity tier (BENCH_r05:
    ``fetch_ms.flat`` ≈ 956 ms of a ~1051 ms tick was this padding).

    Output lanes are exactly the lanes :meth:`_decode_csr` would read,
    in the same order — q-major, segment-minor; within a (query,
    segment) slot, lane ``l < CSR_ROW`` comes from the zone-A identity
    row and later lanes from the slot's zone-B region. ``-1`` holes
    (tombstoned / replication-filtered lanes) ride along, so decoding
    from raw-count cumsum offsets is bit-identical to walking the
    zoned layout. Returns ``(packed [bucket] i32, total i32)``; lanes
    past ``total`` are ``-1``, and ``total > bucket`` means the bucket
    was too small — the caller falls back to the full fetch (slower,
    never wrong).

    Cost: three [bucket] element gathers plus O(M·nseg) prefix sums —
    proportional to the result actually shipped, not the capacity.
    """
    mq, nseg = counts.shape
    cnt = counts.reshape(-1)                       # [M*nseg] raw
    nslots = cnt.shape[0]
    off = jnp.cumsum(cnt) - cnt                    # packed slot starts
    total = cnt.sum(dtype=jnp.int32)
    cnt_b = jnp.maximum(cnt - CSR_ROW, 0)
    prow_b = (cnt_b + (CSR_ROW_B - 1)) // CSR_ROW_B
    rowstart_b = jnp.cumsum(prow_b) - prow_b       # zone-B row starts
    base = mq * CSR_ROW * nseg
    # owner map: packed position -> flattened (q, s) slot. Non-empty
    # slots have strictly increasing starts, so each scatters its id at
    # its start (empty/overflowing slots get dropped OOB marks) and a
    # running max fills the gaps.
    slot_ids = jnp.arange(nslots, dtype=jnp.int32)
    mark = jnp.where(cnt > 0, off, bucket + 1 + slot_ids)
    owner = jax.lax.cummax(
        jnp.zeros(bucket, jnp.int32).at[mark].max(slot_ids, mode="drop")
    )
    j = jnp.arange(bucket, dtype=jnp.int32)
    lane = j - off[owner]
    src = jnp.where(
        lane < CSR_ROW,
        owner * CSR_ROW + lane,
        base + rowstart_b[owner] * CSR_ROW_B + (lane - CSR_ROW),
    )
    vals = flat[jnp.clip(src, 0, flat.shape[0] - 1)]
    return jnp.where(j < total, vals, jnp.int32(-1)), total


@partial(jax.jit, static_argnames=("bucket",))
def _pack_csr_kernel(counts, flat, *, bucket):
    return pack_csr(counts, flat, bucket=bucket)


def padded_slots(counts: np.ndarray) -> int:
    """Host mirror of the zoned layout's flat-slot footprint for RAW
    [M, nseg] counts: zone A is CSR_ROW per (query, segment), zone B
    rounds each past-lane-8 remainder up to whole CSR_ROW_B rows."""
    m, nseg = counts.shape
    rem = np.maximum(counts.astype(np.int64) - CSR_ROW, 0)
    rows = int(((rem + CSR_ROW_B - 1) // CSR_ROW_B).sum())
    return m * CSR_ROW * nseg + rows * CSR_ROW_B


@partial(jax.jit, static_argnames=("ks",))
def _match_dense_kernel(*flat_args, ks):
    return _multi_match(flat_args, ks)


@partial(jax.jit, static_argnames=("ks", "c"))
def _match_sparse_kernel(*flat_args, ks, c):
    return compact_sparse(_multi_match(flat_args, ks), c=c)


@jax.jit
def _scatter_dead(peer_arr, rows):
    """Tombstone ``rows`` (padded with out-of-range indices) in a device
    peer array. ``mode='drop'`` ignores the padding."""
    return peer_arr.at[rows].set(-1, mode="drop")


@jax.jit
def _write_chunk(bufs, chunks, start):
    """Append a host chunk into the persistent insertion-order delta
    buffer at ``start`` (traced scalar — no recompile per position).
    The only per-tick H2D transfer is the chunk itself."""
    # Every index must share ``start``'s dtype: a Python-int 0 would
    # weak-type to int64 under x64 and dynamic_update_slice rejects
    # mixed index dtypes.
    zero = jnp.zeros_like(start)
    return tuple(
        jax.lax.dynamic_update_slice(b, c, (start,) + (zero,) * (b.ndim - 1))
        for b, c in zip(bufs, chunks)
    )


@partial(jax.jit, static_argnames=("cap",))
def _grow_buffers(bufs, cap):
    """Grow the delta buffer to ``cap`` rows on device — no re-upload."""
    pads = (PAD_KEY, np.int64(0), np.int32(-1))
    out = []
    for b, fill in zip(bufs, pads):
        widths = [(0, cap - b.shape[0])] + [(0, 0)] * (b.ndim - 1)
        out.append(jnp.pad(b, widths, constant_values=fill))
    return tuple(out)


@partial(jax.jit, static_argnames=("cap",))
def _alloc_buffers(cap):
    """Fresh all-padding delta buffer, allocated on device (no H2D)."""
    return (
        jnp.full((cap,), PAD_KEY, jnp.int64),
        jnp.zeros((cap,), jnp.int64),
        jnp.full((cap,), -1, jnp.int32),
    )


@partial(jax.jit, static_argnames=("n_buckets",))
def _sort_segment_dev(keys, keys2, peers, n_buckets):
    """Key-sort a segment on device (the delta buffer is insertion-
    ordered; queries need sorted runs), derive its run-remainder
    column and build its bucket probe table — one fused launch.
    Stable, so ties keep insertion order — matching the host's numpy
    mirror."""
    order = jnp.argsort(keys, stable=True)
    sk = keys[order]
    sk2 = keys2[order]
    rem = run_remainders(sk)
    tbl, oflow = probe_tables(sk, sk2, n_buckets=n_buckets)
    return sk, sk2, peers[order], rem, tbl, oflow


@partial(jax.jit, static_argnames=("cap2", "n_buckets"))
def _device_compact(bk, bk2, bp, dk, dk2, dp, cap2, n_buckets):
    """Fold base + delta into a fresh sorted base ENTIRELY on device —
    zero host→device transfer (decisive on tunneled/remote devices
    where a full index upload costs seconds).

    Dead rows (peer < 0) get their key rewritten to the padding
    sentinel, so the stable sort sinks them past every live run and the
    leading ``cap2`` rows are exactly the live index plus padding. The
    host applies the identical transform to its numpy mirror, keeping
    row indices aligned with the device (both sorts are stable). The
    old run-remainder column and probe table are discarded; the new
    base's derive from the folded keys."""
    keys = jnp.concatenate([bk, dk])
    keys2 = jnp.concatenate([bk2, dk2])
    peers = jnp.concatenate([bp, dp])
    keys = jnp.where(peers < 0, PAD_KEY, keys)
    order = jnp.argsort(keys, stable=True)[:cap2]
    sk = keys[order]
    sk2 = keys2[order]
    rem = run_remainders(sk)
    tbl, oflow = probe_tables(sk, sk2, n_buckets=n_buckets)
    return sk, sk2, peers[order], rem, tbl, oflow


@partial(jax.jit, static_argnames=("n_buckets",))
def _probe_only_dev(sk, sk2, n_buckets):
    """Probe table for an already-sorted uploaded segment."""
    return probe_tables(sk, sk2, n_buckets=n_buckets)


# Retrace tripwire: every jitted hot-path kernel is tracked so the test
# suite can fail a change that re-traces per tick instead of per
# capacity tier (utils/retrace.py; tests/test_retrace_budget.py).
for _family, _kernel_fn in {
    "match_dense": _match_dense_kernel,
    "match_sparse": _match_sparse_kernel,
    "match_run_csr": _match_run_csr_kernel,
    "pack_csr": _pack_csr_kernel,
    "scatter_dead": _scatter_dead,
    "write_chunk": _write_chunk,
    "grow_buffers": _grow_buffers,
    "alloc_buffers": _alloc_buffers,
    "sort_segment": _sort_segment_dev,
    "device_compact": _device_compact,
    "probe_only": _probe_only_dev,
}.items():
    retrace.GUARD.register(f"tpu_backend.{_family}", _kernel_fn)
del _family, _kernel_fn


class _CollisionError(Exception):
    """A new cube's key collided with a different stored cube (expected
    ~never at 2^-64 per pair); the caller reseeds and rebuilds."""


# --------------------------------------------------------------------
# Backend
# --------------------------------------------------------------------


class TpuSpatialBackend(SpatialBackend):
    """Device-batched backend. The host-side numpy SoA segments are the
    authority; point queries binary-search them, the batched hot path
    runs on device against their mirror."""

    #: delta rows (live) that trigger a background compaction, as a
    #: fraction of base size
    COMPACT_DELTA_FRACTION = 8
    #: dead base rows that trigger a background compaction (fraction)
    COMPACT_DEAD_FRACTION = 8
    #: delta overrun factor past which bulk loads fold straight into the
    #: base and a delta overrun falls back to a synchronous fold if the
    #: background worker keeps failing
    SYNC_COMPACT_FACTOR = 4
    #: consecutive background-compaction failures before a delta overrun
    #: is allowed to fold synchronously on the owning thread (last
    #: resort: the device is persistently failing, correctness over
    #: latency)
    SYNC_FALLBACK_FAILURES = 3
    #: seconds an in-flight compaction may run before an OVERRUN flush
    #: treats it as wedged and abandons it — a hung device call must not
    #: let the delta log grow without bound
    COMPACT_STALL_SECS = 120.0
    def __init__(self, cube_size: int, compact_threshold: int | None = None):
        super().__init__(cube_size)
        self._world_ids: dict[str, int] = {}
        self._peer_ids: dict[uuid_mod.UUID, int] = {}
        self._peer_list: list[uuid_mod.UUID] = []
        # world id → live-row refcount per peer id (query_world /
        # is_subscribed_any in O(1), the AreaMap subscribed_peers view,
        # area_map.rs:10-17)
        self._world_peers: dict[int, Counter] = {}
        self._seed = 0
        self._dirty = True
        self._compact_threshold_override = compact_threshold

        # base segment (host authority, sorted by key). _bw/_bxyz are
        # the exact-identity authority (point queries, collision
        # checks); _bk2 mirrors the device's second-key column.
        self._bk = np.empty(0, np.int64)
        self._bk2 = np.empty(0, np.int64)
        self._bw = np.empty(0, np.int32)
        self._bxyz = np.empty((0, 3), np.int64)
        self._bp = np.empty(0, np.int32)
        self._base_live = 0
        self._base_dead = 0
        self._base_k = 1
        self._base_bundle: dict | None = None
        #: host base newer than the device twin (upload owed at flush)
        self._base_stale = False
        self._pending_dead: list[int] = []

        # delta log (host authority, insertion order, capacity doubling)
        self._dcap = 0
        self._dk = np.empty(0, np.int64)
        self._dk2 = np.empty(0, np.int64)
        self._dw = np.empty(0, np.int32)
        self._dxyz = np.empty((0, 3), np.int64)
        self._dp = np.empty(0, np.int32)
        self._dn = 0
        self._delta_live = 0
        self._delta_index: dict[tuple[int, int], int] = {}  # (key,pid)→row
        self._delta_keyrow: dict[int, int] = {}  # key → first row (cube id)
        self._delta_key_count: Counter = Counter()  # key → rows (incl. dead)
        self._delta_max_run = 1
        self._delta_stale = False
        # device twin of the log: persistent insertion-order buffer
        # (only new-row chunks ever transfer) + its key-sorted view
        self._delta_buf: tuple | None = None
        self._delta_buf_cap = 0
        self._delta_built_n = 0  # log rows present in the device buffer
        self._pending_delta_dead: list[int] = []
        self._delta_bundle: dict | None = None
        self._delta_k = 1

        # background compaction
        self._compaction: dict | None = None
        self._replay: list[tuple[int, int]] = []
        self._epoch = 0

        self.compactions = 0
        self.compaction_failures = 0
        self._failed_streak = 0
        # CSR result-capacity hint for the delivery path; grows on
        # overflow (collect_local_batch)
        self._delivery_cap = 4096
        # the DELTA sub-batch path sizes its CSR results off its own
        # hint: dirty partitions are orders of magnitude smaller than
        # full ticks, and letting them decay the main hint would both
        # thrash capacity tiers while it halves down and starve the
        # next full-recompute tick into an overflow retry
        self._delta_delivery_cap = 4096

        # On-device result compaction (pack_csr): pack the lanes the
        # decoder will read into a power-of-two bucket sized to the
        # tick's ACTUAL fan-out and fetch only that. Applies once the
        # capacity tier clears min_cap (below it the prefetched full
        # fetch wins — the pack dispatch costs a round trip) AND the
        # bucket saves at least 2x the bytes. min_bucket floors the
        # bucket ladder so steady traffic reuses a handful of compiled
        # pack shapes (retrace budget).
        self.compact_fetch = True
        self.compact_fetch_min_cap = 1 << 15
        self.compact_min_bucket = 1 << 10
        self.compact_fetches = 0
        self.full_fetches = 0
        #: what the LAST collect shipped over the link (the tick
        #: batcher reports these as tick.fetch_bytes /
        #: tick.compaction_bucket)
        self.last_collect_stats = {
            "fetch_slots": 0, "fetch_bytes": 0, "compaction_bucket": 0,
        }
        # Per-tick device timing split (ISSUE 7): dispatch brackets
        # {encode, h2d-enqueue, d2h-prefetch} walls into a dict that
        # RIDES THE HANDLE (a FIFO deque was the previous design — it
        # desynced when a collect errored before reaching its pop,
        # silently mis-attributing every later tick's split; handle-
        # carried timing makes pairing structural at any pipeline
        # depth). Collect adds the device wait + fetch walls and
        # publishes the merged dict as ``last_device_timing`` for
        # DeviceTelemetry to tag onto the tick trace. These are
        # HOST-side brackets of the existing instrumentation points,
        # not profiler truth — on a tunneled device the "compute" wall
        # includes the link.
        self._last_prefetch_ms = 0.0
        self.last_device_timing: dict = {}
        #: capacity tier of the LAST dispatch (retrace spans tag it —
        #: a tier first-hit is the expected compile trigger)
        self.last_dispatch_tier: dict = {}
        #: dispatches that arrived pre-encoded as staged columnar
        #: arrays (engine/staging.py) vs. as LocalQuery object lists —
        #: the bench smoke gate asserts the staged path actually fired
        self.staged_dispatches = 0
        self.list_dispatches = 0
        #: mixed-kind batches routed through the query-library probe
        #: expansion (queries/expand.py) — pure-radius ticks never
        #: touch that path, so the bench parity leg can assert it fired
        self.kind_expansions = 0

        # Delta ticks (ROADMAP 2, spatial/delta_ticks.py): per-cube
        # dirty tracking from the churn stream + the result-reuse
        # cache. OFF by default — the dispatch/collect pipeline is
        # byte-for-byte the pre-delta path until configure_delta_ticks
        # enables it (server wiring / bench), and every mutation-path
        # mark is gated on the flag so the disabled overhead is one
        # branch per mutation batch.
        self._delta_ticks = False
        #: churn fraction above which a delta structure falls back to
        #: the full path: tombstone-scatter delta sync reverts to the
        #: device re-sort past this fraction of the built log, and the
        #: entity plane mirrors it for its dirty-closure sub-tick
        self.delta_rebuild_threshold = 0.5
        self._coherence = TemporalCoherence()
        #: host mirror of the device delta sort order ((built, cap),
        #: row → sorted position), backing the O(K) tombstone scatter
        #: into the persistent sorted segment
        self._delta_sort_pos: tuple | None = None
        self.delta_reused = 0
        self.delta_recomputed = 0
        self.delta_fallbacks = 0
        self.delta_sync_scatters = 0
        self.delta_sync_sorts = 0
        #: the LAST delta dispatch's partition (tick.delta span tags)
        self.last_delta_stats: dict = {}
        #: the LAST delta-twin sync's path + wall (bench attribution)
        self.last_delta_sync: dict = {}

        # pid → base rows: lazily built per base epoch (argsort of the
        # peer column, O(S log S) once), then each eviction is two
        # binary searches + a small gather instead of an O(S) scan.
        # Tombstones only ever rewrite peers to -1, so entries can go
        # stale-dead but never point at a *different* peer; lookups
        # re-check liveness against the current peer column.
        self._base_pid_order: tuple[np.ndarray, np.ndarray] | None = None
        # pid → delta rows, maintained incrementally on append.
        self._delta_pid_rows: dict[int, list[int]] = {}

    # region: interning

    def _world_id(self, world: str) -> int:
        wid = self._world_ids.get(world)
        if wid is None:
            wid = self._world_ids[world] = len(self._world_ids)
            self._world_peers[wid] = Counter()
        return wid

    def _peer_id(self, peer: uuid_mod.UUID) -> int:
        pid = self._peer_ids.get(peer)
        if pid is None:
            pid = self._peer_ids[peer] = len(self._peer_list)
            self._peer_list.append(peer)
        return pid

    def _key_of(self, wid: int, cube: Cube) -> int:
        return int(spatial_keys(
            np.array([wid], np.int32),
            np.array([cube], np.int64),
            self._seed,
        )[0])

    def supports_staged_dispatch(self) -> bool:
        return True

    def supports_delta_ticks(self) -> bool:
        """Whether this backend can serve delta ticks (result reuse +
        incremental delta sync). The sharded backend conservatively
        says no for now — reuse must be correct before it is fast."""
        return True

    def configure_delta_ticks(self, mode: str) -> bool:
        """Arm/disarm delta ticks: ``on``/``auto`` enable when the
        backend supports them, ``off`` restores the pre-delta pipeline
        byte for byte. Enabling starts from a cold cache (mutations
        made while tracking was off were never marked). Returns the
        resulting state."""
        want = mode in ("on", "auto") and self.supports_delta_ticks()
        if want and not self._delta_ticks:
            self._coherence.invalidate_all()
        self._delta_ticks = want
        return want

    def interning_maps(self):
        """Enqueue-time interning contract (engine/staging.py): both
        dicts are owned by the event-loop thread — router enqueue,
        subscription mutations and dispatch all run there — and are
        append-only for the backend's lifetime, so ids interned at
        message arrival stay valid at flush time."""
        return self._world_ids, self._peer_ids

    # endregion

    # region: host search

    def _base_run(self, key: int) -> tuple[int, int]:
        lo = int(np.searchsorted(self._bk, key, side="left"))
        hi = int(np.searchsorted(self._bk, key, side="right"))
        return lo, hi

    def _find_live_row(self, key: int, wid: int, cube: Cube, pid: int):
        """→ ('base', row) | ('delta', row) | None. Raises
        :class:`_CollisionError` if ``key`` is held by a different
        cube."""
        lo, hi = self._base_run(key)
        if lo < hi:
            if self._bw[lo] != wid or (
                self._bxyz[lo, 0] != cube[0]
                or self._bxyz[lo, 1] != cube[1]
                or self._bxyz[lo, 2] != cube[2]
            ):
                raise _CollisionError
            j = np.flatnonzero(self._bp[lo:hi] == pid)
            if j.size:
                return ("base", lo + int(j[0]))
        drow = self._delta_keyrow.get(key)
        if drow is not None:
            if self._dw[drow] != wid or (
                self._dxyz[drow, 0] != cube[0]
                or self._dxyz[drow, 1] != cube[1]
                or self._dxyz[drow, 2] != cube[2]
            ):
                raise _CollisionError
            row = self._delta_index.get((key, pid))
            if row is not None:
                return ("delta", row)
        return None

    # endregion

    # region: mutations

    def add_subscription(
        self, world: str, peer: uuid_mod.UUID, pos: Vector3 | Cube
    ) -> bool:
        cube = to_cube(pos, self.cube_size)
        wid = self._world_id(world)
        pid = self._peer_id(peer)
        while True:
            key = self._key_of(wid, cube)
            try:
                if key == int(PAD_KEY):
                    raise _CollisionError
                if self._find_live_row(key, wid, cube, pid) is not None:
                    return False
            except _CollisionError:
                self._reseed_rebuild()
                continue
            break
        self._delta_append(key, wid, cube, pid)
        self._world_peers[wid][pid] += 1
        self._dirty = True
        return True

    def remove_subscription(
        self, world: str, peer: uuid_mod.UUID, pos: Vector3 | Cube
    ) -> bool:
        cube = to_cube(pos, self.cube_size)
        wid = self._world_ids.get(world)
        pid = self._peer_ids.get(peer)
        if wid is None or pid is None:
            return False
        key = self._key_of(wid, cube)
        try:
            found = self._find_live_row(key, wid, cube, pid)
        except _CollisionError:
            # The colliding cube is someone else's; ours isn't stored.
            return False
        if found is None:
            return False
        self._tombstone(found, key, pid)
        self._drop_world_peer(wid, pid, 1)
        self._dirty = True
        return True

    def _peer_base_rows(self, pid: int) -> np.ndarray:
        """Live base rows held by ``pid``: two binary searches + a small
        gather against a per-epoch pid-sorted view (built lazily, once
        per base install) instead of an O(S) column scan per eviction —
        a disconnect storm at 1M rows would otherwise stall the event
        loop scanning 4 MB per peer."""
        if self._bp.size == 0:
            return np.empty(0, np.intp)
        if self._base_pid_order is None:
            order = np.argsort(self._bp, kind="stable")
            self._base_pid_order = (order, self._bp[order])
        order, sorted_p = self._base_pid_order
        lo = int(np.searchsorted(sorted_p, pid, side="left"))
        hi = int(np.searchsorted(sorted_p, pid, side="right"))
        rows = order[lo:hi]
        # ``sorted_p`` is a build-time snapshot: rows tombstoned since
        # then still appear under their old pid — re-check liveness.
        return rows[self._bp[rows] == pid]

    def remove_peer(self, peer: uuid_mod.UUID) -> bool:
        pid = self._peer_ids.get(peer)
        if pid is None:
            return False
        rows_b = self._peer_base_rows(pid)
        drows = self._delta_pid_rows.pop(pid, None)
        if drows is not None:
            rows_d = np.asarray(drows, np.intp)
            rows_d = rows_d[self._dp[rows_d] == pid]
        else:
            rows_d = np.empty(0, np.intp)
        if rows_b.size == 0 and rows_d.size == 0:
            return False
        if self._delta_ticks:
            self._coherence.note_keys(np.concatenate([
                self._bk[rows_b], self._dk[rows_d]
            ]))

        in_flight = self._compaction is not None
        if rows_b.size:
            self._bp[rows_b] = -1
            self._pending_dead.extend(int(r) for r in rows_b)
            self._base_dead += int(rows_b.size)
            self._base_live -= int(rows_b.size)
            if in_flight:
                self._replay.extend(
                    (int(self._bk[r]), pid) for r in rows_b
                )
        if rows_d.size:
            consumed = self._compaction["consumed_dn"] if in_flight else 0
            for r in rows_d:
                r = int(r)
                self._dp[r] = -1
                self._delta_index.pop((int(self._dk[r]), pid), None)
                if r < self._delta_built_n:
                    self._pending_delta_dead.append(r)
                if in_flight and r < consumed:
                    self._replay.append((int(self._dk[r]), pid))
            self._delta_live -= int(rows_d.size)
            self._delta_stale = True

        # world-level refcounts: drop this peer from every touched world
        wids = np.unique(np.concatenate([
            self._bw[rows_b], self._dw[rows_d]
        ])) if rows_b.size or rows_d.size else ()
        for wid in wids:
            self._world_peers[int(wid)].pop(pid, None)

        self._dirty = True
        return True

    def _delta_append(self, key: int, wid: int, cube: Cube, pid: int) -> None:
        if self._dn == self._dcap:
            self._grow_delta(max(1024, self._dcap * 2))
        row = self._dn
        self._dk[row] = key
        self._dw[row] = wid
        self._dxyz[row] = cube
        self._dp[row] = pid
        self._dn += 1
        self._delta_live += 1
        if self._delta_ticks:
            self._coherence.note_key(key)
        self._delta_index[(key, pid)] = row
        self._delta_pid_rows.setdefault(pid, []).append(row)
        self._delta_keyrow.setdefault(key, row)
        run = self._delta_key_count[key] + 1
        self._delta_key_count[key] = run
        if run > self._delta_max_run:
            self._delta_max_run = run
        self._delta_stale = True

    def _grow_delta(self, cap: int) -> None:
        def grow(arr, shape, dtype):
            out = np.empty(shape, dtype)
            out[:self._dn] = arr[:self._dn]
            return out

        self._dk = grow(self._dk, cap, np.int64)
        self._dk2 = grow(self._dk2, cap, np.int64)
        self._dw = grow(self._dw, (cap,), np.int32)
        self._dxyz = grow(self._dxyz, (cap, 3), np.int64)
        self._dp = grow(self._dp, (cap,), np.int32)
        self._dcap = cap

    def _tombstone(self, found: tuple[str, int], key: int, pid: int) -> None:
        seg, row = found
        if self._delta_ticks:
            self._coherence.note_key(key)
        in_flight = self._compaction is not None
        if seg == "base":
            self._bp[row] = -1
            self._pending_dead.append(row)
            self._base_dead += 1
            self._base_live -= 1
            if in_flight:
                self._replay.append((key, pid))
        else:
            self._dp[row] = -1
            self._delta_live -= 1
            self._delta_index.pop((key, pid), None)
            if row < self._delta_built_n:
                self._pending_delta_dead.append(row)
            self._delta_stale = True
            if in_flight and row < self._compaction["consumed_dn"]:
                self._replay.append((key, pid))

    def _drop_world_peer(self, wid: int, pid: int, n: int) -> None:
        wp = self._world_peers[wid]
        wp[pid] -= n
        if wp[pid] <= 0:
            del wp[pid]

    # endregion

    # region: bulk mutations (vectorized loaders)

    def bulk_add_subscriptions(self, world, peers, cubes) -> int:
        """Bulk-load peers[i] → cube rows [N, 3] (already quantized).
        Vectorized: interning aside, no per-row Python. Loader for
        benchmarks, churn workloads and snapshot restore."""
        cubes = np.ascontiguousarray(cubes, dtype=np.int64)
        n = len(cubes)
        if n == 0:
            return 0
        wid = self._world_id(world)
        pids = self._intern_peers(peers)

        while True:
            keys = spatial_keys(
                np.full(n, wid, np.int32), cubes, self._seed
            )
            try:
                new_rows = self._bulk_dedupe(keys, pids, cubes, wid)
            except _CollisionError:
                self._reseed_rebuild()
                continue
            break

        if new_rows.size == 0:
            return 0
        if self._delta_ticks:
            self._coherence.note_keys(keys[new_rows])
        self._bulk_append(
            keys[new_rows], np.full(new_rows.size, wid, np.int32),
            cubes[new_rows], pids[new_rows],
        )
        # world-level refcounts, vectorized into the Counter
        u, c = np.unique(pids[new_rows], return_counts=True)
        counts = dict(zip(u.tolist(), c.tolist()))
        wp = self._world_peers[wid]
        if wp:
            wp.update(counts)
        else:
            self._world_peers[wid] = Counter(counts)
        self._dirty = True
        return int(new_rows.size)

    def bulk_remove_subscriptions(self, world, peers, cubes) -> int:
        """Vectorized unsubscribe of peers[i] from cube rows [N, 3].
        Returns the number of subscriptions actually removed."""
        cubes = np.ascontiguousarray(cubes, dtype=np.int64)
        n = len(cubes)
        wid = self._world_ids.get(world)
        if n == 0 or wid is None:
            return 0
        pids = np.fromiter(
            (self._peer_ids.get(p, -1) for p in peers), np.int64, count=n
        )
        keys = spatial_keys(np.full(n, wid, np.int32), cubes, self._seed)

        # intra-batch dedupe of (key, pid) pairs, drop unknown peers
        valid = pids >= 0
        if not valid.any():
            return 0
        k_, p_ = keys[valid], pids[valid]
        order = np.lexsort((p_, k_))
        ks_, ps_ = k_[order], p_[order]
        first = np.ones(ks_.size, bool)
        first[1:] = (ks_[1:] != ks_[:-1]) | (ps_[1:] != ps_[:-1])
        ks_, ps_ = ks_[first], ps_[first]

        in_flight = self._compaction is not None
        consumed = self._compaction["consumed_dn"] if in_flight else 0
        removed_pids: list[np.ndarray] = []

        # base rows: vectorized run-candidate join on (key, pid)
        bn = self._bk.size
        base_hit = np.zeros(ks_.size, bool)
        if bn:
            lo = np.searchsorted(self._bk, ks_, side="left")
            hi = np.searchsorted(self._bk, ks_, side="right")
            runs = hi - lo
            total = int(runs.sum())
            if total:
                qidx = np.repeat(np.arange(ks_.size), runs)
                rows = np.repeat(lo, runs) + (
                    np.arange(total) - np.repeat(np.cumsum(runs) - runs, runs)
                )
                match = self._bp[rows] == ps_[qidx]
                rows_found = rows[match]
                base_hit[qidx[match]] = True
                if rows_found.size:
                    if self._delta_ticks:
                        self._coherence.note_keys(self._bk[rows_found])
                    self._bp[rows_found] = -1
                    self._pending_dead.extend(rows_found.tolist())
                    self._base_dead += int(rows_found.size)
                    self._base_live -= int(rows_found.size)
                    removed_pids.append(ps_[qidx[match]])
                    if in_flight:
                        self._replay.extend(zip(
                            self._bk[rows_found].tolist(),
                            ps_[qidx[match]].tolist(),
                        ))

        # delta rows: dict lookups for the batch rows the base missed
        delta_removed = []
        delta_removed_keys: list[int] = []
        if self._delta_index:
            miss = np.flatnonzero(~base_hit)
            for i in miss:
                pair = (int(ks_[i]), int(ps_[i]))
                row = self._delta_index.pop(pair, None)
                if row is None:
                    continue
                self._dp[row] = -1
                delta_removed.append(pair[1])
                delta_removed_keys.append(pair[0])
                if row < self._delta_built_n:
                    self._pending_delta_dead.append(row)
                if in_flight and row < consumed:
                    self._replay.append(pair)
            if delta_removed:
                if self._delta_ticks:
                    self._coherence.note_keys(delta_removed_keys)
                self._delta_live -= len(delta_removed)
                self._delta_stale = True
                removed_pids.append(np.asarray(delta_removed, np.int64))

        if not removed_pids:
            return 0
        all_pids = np.concatenate(removed_pids)
        u, c = np.unique(all_pids, return_counts=True)
        for pid, cnt in zip(u.tolist(), c.tolist()):
            self._drop_world_peer(wid, int(pid), cnt)
        self._dirty = True
        return int(all_pids.size)

    def bulk_move_subscriptions(
        self, world, rem_peers, rem_cubes, add_peers, add_cubes,
    ) -> tuple[int, int]:
        """Moving-object churn ingest (entities/plane.py): retire
        ``rem_peers[i] → rem_cubes[i]`` rows and insert ``add_peers[i]
        → add_cubes[i]`` rows in one call, both through the base+delta
        path — tombstones into whichever segment holds each retired
        row, appends into the delta log (whose growth drives the normal
        compaction policy, so sustained churn exercises the LSM fold
        exactly like any other write stream). Removes run FIRST so a
        peer hopping cubes within one batch never momentarily holds
        two rows. Returns ``(removed, added)``."""
        removed = self.bulk_remove_subscriptions(world, rem_peers, rem_cubes)
        added = self.bulk_add_subscriptions(world, add_peers, add_cubes)
        return removed, added

    def _intern_peers(self, peers) -> np.ndarray:
        peer_ids = self._peer_ids
        peer_list = self._peer_list
        if not peer_ids:
            # Fresh-index fast path (1M-entity bulk load): one C-speed
            # dict build. Intra-batch duplicate peers map to their last
            # slot; earlier slots stay as unreferenced list entries.
            n0 = len(peer_list)
            peer_ids.update(zip(peers, range(n0, n0 + len(peers))))
            peer_list.extend(peers)
            if len(peer_ids) == len(peer_list):
                return np.arange(n0, n0 + len(peers), dtype=np.int64)
            return np.fromiter(
                (peer_ids[p] for p in peers), np.int64, count=len(peers)
            )
        out = np.empty(len(peers), np.int64)
        for i, p in enumerate(peers):
            pid = peer_ids.get(p)
            if pid is None:
                pid = peer_ids[p] = len(peer_list)
                peer_list.append(p)
            out[i] = pid
        return out

    def _bulk_dedupe(self, keys, pids, cubes, wid) -> np.ndarray:
        """Indices of rows that are new (not duplicates within the batch
        nor of existing live rows). Raises on any key collision."""
        n = len(keys)
        # intra-batch: keep the first row of each (key, pid) pair
        order = np.lexsort((pids, keys))
        ks, ps = keys[order], pids[order]
        first = np.ones(n, bool)
        first[1:] = (ks[1:] != ks[:-1]) | (ps[1:] != ps[:-1])
        # same key must mean same cube within the batch
        same_key = ks[1:] == ks[:-1]
        if same_key.any():
            a, b = order[1:][same_key], order[:-1][same_key]
            if (cubes[a] != cubes[b]).any():
                raise _CollisionError
        if (keys == int(PAD_KEY)).any():
            raise _CollisionError
        reps = order[first]

        # vs existing live rows: candidate extraction (only the base
        # runs + delta rows matching batch keys — O(hits), not O(S)),
        # then a union-rank merge join over (key, pid)
        self._check_batch_collisions(keys[reps], cubes[reps], wid)
        exist_k, exist_p = self._candidate_pairs(keys[reps])
        if exist_k.size:
            uniq = np.unique(np.concatenate([exist_k, keys[reps]]))
            ex_comb = (
                np.searchsorted(uniq, exist_k).astype(np.uint64) << np.uint64(32)
            ) | exist_p.astype(np.uint64)
            q_comb = (
                np.searchsorted(uniq, keys[reps]).astype(np.uint64) << np.uint64(32)
            ) | pids[reps].astype(np.uint64)
            ex_comb.sort()
            pos = np.searchsorted(ex_comb, q_comb)
            pos = np.minimum(pos, ex_comb.size - 1)
            member = ex_comb[pos] == q_comb
            reps = reps[~member]
        return reps

    def _candidate_pairs(self, qkeys) -> tuple[np.ndarray, np.ndarray]:
        """Live (key, pid) rows whose key appears in ``qkeys`` —
        the only rows a batch membership check can hit."""
        parts_k, parts_p = [], []
        bn = self._bk.size
        if bn:
            lo = np.searchsorted(self._bk, qkeys, side="left")
            hi = np.searchsorted(self._bk, qkeys, side="right")
            runs = hi - lo
            total = int(runs.sum())
            if total:
                # row indices of every run, concatenated
                starts = np.repeat(lo, runs)
                offs = np.arange(total) - np.repeat(
                    np.cumsum(runs) - runs, runs
                )
                rows = starts + offs
                live = self._bp[rows] >= 0
                parts_k.append(self._bk[rows[live]])
                parts_p.append(self._bp[rows[live]])
        dn = self._dn
        if dn:
            hit = np.isin(self._dk[:dn], qkeys) & (self._dp[:dn] >= 0)
            if hit.any():
                parts_k.append(self._dk[:dn][hit])
                parts_p.append(self._dp[:dn][hit])
        if not parts_k:
            return np.empty(0, np.int64), np.empty(0, np.int32)
        return np.concatenate(parts_k), np.concatenate(parts_p)

    def _check_batch_collisions(self, keys, cubes, wid) -> None:
        bn = self._bk.size
        if bn:
            lo = np.searchsorted(self._bk, keys, side="left")
            li = np.minimum(lo, bn - 1)
            hit = self._bk[li] == keys
            if hit.any():
                ok = (
                    (self._bw[li[hit]] == wid)
                    & (self._bxyz[li[hit]] == cubes[hit]).all(axis=1)
                )
                if not ok.all():
                    raise _CollisionError
        if self._delta_keyrow:
            # only batch keys actually present in the delta need a look
            dkeys = np.fromiter(
                self._delta_keyrow, np.int64, count=len(self._delta_keyrow)
            )
            for i in np.flatnonzero(np.isin(keys, dkeys)):
                drow = self._delta_keyrow[int(keys[i])]
                if self._dw[drow] != wid or (
                    self._dxyz[drow] != cubes[i]
                ).any():
                    raise _CollisionError

    def _live_pairs(self) -> tuple[np.ndarray, np.ndarray]:
        """All live (key, pid) rows across base + delta."""
        live_b = self._bp >= 0
        live_d = self._dp[:self._dn] >= 0
        return (
            np.concatenate([self._bk[live_b], self._dk[:self._dn][live_d]]),
            np.concatenate([self._bp[live_b], self._dp[:self._dn][live_d]]),
        )

    def _bulk_append(self, keys, wids, cubes, pids) -> None:
        n = len(keys)
        threshold = self._compact_threshold()
        total_live = self._base_live + self._delta_live
        if (
            n > self.SYNC_COMPACT_FACTOR * threshold
            or self._delta_live + n >= self.SYNC_COMPACT_FACTOR * threshold
            or (
                self._base_stale
                and self._delta_live + n >= max(total_live // 32, 1024)
            )
        ):
            # Fold straight into a new base when: the load is huge
            # (initial index build, snapshot restore); OR the delta
            # would overrun into sync-fallback territory anyway — e.g.
            # per-world bulk calls that are individually under the
            # limit but jointly a full rebuild; OR an upload is already
            # owed (mid-load-phase) and the pending rows are a real
            # fraction (>= 1/32) of the index, so folding costs one more
            # host sort but zero extra device traffic — the upload is
            # DEFERRED to the next flush either way, so a whole load
            # phase (even 64+ small per-world calls) ships ONE base and
            # ends fully compacted: no trailing delta segment slowing
            # every subsequent query batch, no delta-tier kernel
            # compiles on the flush path. No delta dict fills, one
            # vectorized host sort.
            self._rebuild_base_with(keys, wids, cubes, pids)
            return
        if self._dn + n > self._dcap:
            self._grow_delta(next_pow2(self._dn + n, 1024))
        a, b = self._dn, self._dn + n
        self._dk[a:b] = keys
        self._dw[a:b] = wids
        self._dxyz[a:b] = cubes
        self._dp[a:b] = pids
        rows = range(a, b)
        idx = self._delta_index
        keyrow = self._delta_keyrow
        pid_rows = self._delta_pid_rows
        for row, key, pid in zip(rows, keys.tolist(), pids.tolist()):
            idx[(key, pid)] = row
            keyrow.setdefault(key, row)
            pid_rows.setdefault(pid, []).append(row)
        kc = self._delta_key_count
        u, c = np.unique(keys, return_counts=True)
        for key, cnt in zip(u.tolist(), c.tolist()):
            run = kc[key] + cnt
            kc[key] = run
            if run > self._delta_max_run:
                self._delta_max_run = run
        self._dn = b
        self._delta_live += n
        self._delta_stale = True

    def _rebuild_base_with(self, keys, wids, cubes, pids) -> None:
        """Synchronously fold (live base + live delta + new rows) into a
        fresh sorted base; clears the delta."""
        if self._compaction is not None:
            self._abandon_compaction()
        live_b = self._bp >= 0
        live_d = self._dp[:self._dn] >= 0
        all_k = np.concatenate([self._bk[live_b], self._dk[:self._dn][live_d], keys])
        all_w = np.concatenate([self._bw[live_b], self._dw[:self._dn][live_d], wids])
        all_x = np.concatenate(
            [self._bxyz[live_b], self._dxyz[:self._dn][live_d], cubes]
        )
        all_p = np.concatenate([
            self._bp[live_b], self._dp[:self._dn][live_d],
            pids.astype(np.int32),
        ])
        self._install_base(*_sort_segment(all_k, all_w, all_x, all_p))
        self._clear_delta()
        self._dirty = True

    # endregion

    # region: reseed (hash collision — expected ~never)

    def _reseed_rebuild(self) -> None:
        """A key collision was detected: bump the seed until every live
        cube gets a distinct non-sentinel key, then rebuild the base."""
        if self._compaction is not None:
            self._abandon_compaction()
        live_b = self._bp >= 0
        live_d = self._dp[:self._dn] >= 0
        w = np.concatenate([self._bw[live_b], self._dw[:self._dn][live_d]])
        x = np.concatenate([self._bxyz[live_b], self._dxyz[:self._dn][live_d]])
        p = np.concatenate([self._bp[live_b], self._dp[:self._dn][live_d]])
        while True:
            self._seed += 1
            keys = spatial_keys(w.astype(np.int32), x, self._seed)
            order = np.argsort(keys, kind="stable")
            ks = keys[order]
            same = ks[1:] == ks[:-1]
            bad = (ks == int(PAD_KEY)).any()
            if same.any():
                a, b = order[1:][same], order[:-1][same]
                bad = bad or (w[a] != w[b]).any() or (x[a] != x[b]).any()
            if not bad:
                break
        self._install_base(ks, w[order].astype(np.int32), x[order],
                           p[order].astype(np.int32))
        self._clear_delta()
        self._dirty = True

    # endregion

    # region: flush / compaction

    def _compact_threshold(self) -> int:
        if self._compact_threshold_override is not None:
            return self._compact_threshold_override
        return max(4096, self._bk.size // self.COMPACT_DELTA_FRACTION)

    def flush(self) -> None:
        """Make all prior mutations visible to device queries. Cost is
        O(churn since last flush) plus, rarely, a compaction."""
        if self._compaction is not None and self._compaction["done"].is_set():
            err = self._swap_compaction()
            if err is not None:
                _log.warning("background compaction failed, will retry: %s", err)

        # 0. deferred base upload (bulk load / restore / sync rebuild)
        # — designated full-path site: the base was rebuilt wholesale
        # off the tick path and owes the device exactly one ship
        self._upload_stale_base()  # wql: allow(full-rebuild-on-tick)

        if not self._dirty:
            return
        self._dirty = False

        # 1. tombstones → one device scatter
        if self._pending_dead and self._base_bundle is not None:
            rows = np.asarray(self._pending_dead, np.int32)
            self._base_bundle = self._scatter_base_dead(self._base_bundle, rows)
        self._pending_dead.clear()

        # 2. delta device twin: upload new rows, scatter tombstones,
        # re-sort on device — O(churn) transfer
        if self._delta_stale:
            self._delta_stale = False
            self._sync_delta()

        # 3. compaction policy. delta_dead matters too: under steady
        # resubscribe churn (move out of a cube, into another) the live
        # count stays flat while tombstoned log rows pile up — without
        # the delta_dead trigger the log, its device buffer and the
        # per-flush device sort grow without bound.
        threshold = self._compact_threshold()
        dead_threshold = max(
            4096, self._bk.size // self.COMPACT_DEAD_FRACTION
        )
        delta_dead = self._dn - self._delta_live
        # live OR tombstone-dominated overrun: under resubscribe churn
        # _delta_live stays flat while dead log rows pile up — the log
        # (_dn) must bound too
        overrun = (
            self._delta_live > self.SYNC_COMPACT_FACTOR * threshold
            or delta_dead > self.SYNC_COMPACT_FACTOR * dead_threshold
        )
        if overrun and self._compaction is not None:
            stalled = time.monotonic() - self._compaction["started"]
            if stalled > self.COMPACT_STALL_SECS:
                # A worker that hangs (device call never returns) would
                # otherwise block both policy branches forever while the
                # delta grows without bound. Orphan it: the epoch bump
                # means its eventual result can never swap in.
                _log.warning(
                    "abandoning wedged compaction after %.0fs", stalled
                )
                self._abandon_compaction()
                self.compaction_failures += 1
                self._failed_streak += 1
        if self._compaction is None:
            if overrun and self._failed_streak >= self.SYNC_FALLBACK_FAILURES:
                # Last resort: the delta overran AND the background
                # worker keeps failing or hanging — fold on the owning
                # thread so a persistent device fault surfaces
                # synchronously instead of the delta growing forever. A
                # healthy overrun (churn outpacing one compaction) stays
                # off the event loop: the oversized delta keeps serving
                # correctly while the next background fold catches up.
                self._compact_sync()  # wql: allow(full-rebuild-on-tick) — last-resort sync fold (persistent device failure)
            elif (
                (
                    self._delta_live > threshold
                    or self._base_dead > dead_threshold
                    or delta_dead > dead_threshold
                )
                and (self._base_dead or self._dn)
            ):
                self._start_compaction()

    def _sync_delta(self) -> None:
        """Bring the device delta twin up to date with the host log.
        Transfers only the NEW rows chunk + tombstone indices; the
        key-sort runs on device (one fused launch per flush).

        With delta ticks armed, a flush whose only changes are
        tombstones skips the re-sort entirely: the persistent SORTED
        segment takes one O(K) peer scatter at host-mapped sorted
        positions (keys never change, so the run structure and probe
        table stay valid — the same contract the base segment's
        tombstone scatter has always relied on). Past
        ``delta_rebuild_threshold`` of the built log the full re-sort
        path takes over (tombstone debt — one sort re-amortizes it)."""
        dn = self._dn
        if dn == 0:
            self._delta_buf = None
            self._delta_buf_cap = 0
            self._delta_built_n = 0
            self._delta_bundle = None
            self._delta_sort_pos = None
            self._pending_delta_dead.clear()
            return

        if self._delta_tombstones_only():
            self._scatter_sorted_tombstones()
            return

        built = self._delta_built_n
        chunk_n = next_pow2(dn - built, 8) if dn > built else 0
        cap_needed = next_pow2(max(dn, built + chunk_n), 1024)
        if self._delta_buf is None:
            self._delta_buf = self._alloc_delta_buffer(cap_needed)
            self._delta_buf_cap = cap_needed
        elif cap_needed > self._delta_buf_cap:
            self._delta_buf = self._grow_delta_buffer(
                self._delta_buf, cap_needed
            )
            self._delta_buf_cap = cap_needed

        if dn > built:
            # second keys are computed lazily here (vectorized over the
            # new chunk) rather than per-row on the append hot path
            self._dk2[built:dn] = spatial_keys2(
                self._dw[built:dn], self._dxyz[built:dn], self._seed
            )
            chunk = (
                pad_to(self._dk[built:dn], chunk_n, PAD_KEY),
                pad_to(self._dk2[built:dn], chunk_n, np.int64(0)),
                pad_to(self._dp[built:dn], chunk_n, np.int32(-1)),
            )
            self._delta_buf = self._write_delta_chunk(
                self._delta_buf, chunk, built
            )
            self._delta_built_n = dn

        if self._pending_delta_dead:
            rows = np.asarray(self._pending_delta_dead, np.int32)
            rows = pad_to(rows, next_pow2(rows.size),
                          np.int32(self._delta_buf_cap))
            self._delta_buf = (
                *self._delta_buf[:2],
                self._scatter_delta_dead(self._delta_buf[2], rows),
            )
            self._pending_delta_dead.clear()

        self._delta_k = next_pow2(self._delta_max_run, 8)
        t0 = time.perf_counter()
        self._delta_bundle = {
            # designated full-rebuild site: new rows were appended (or
            # tombstone debt crossed the threshold) — the sorted
            # segment must rebuild from the insertion-order buffer
            "dev": self._sort_delta(  # wql: allow(full-rebuild-on-tick)
                self._delta_buf,
                probe_buckets_for(len(self._delta_key_count)),
            ),
            "cap": self._delta_buf_cap,
        }
        self._delta_sort_pos = None  # mapping is for the OLD sort state
        self.delta_sync_sorts += 1
        self.last_delta_sync = {
            "path": "sort",
            "ms": round((time.perf_counter() - t0) * 1e3, 3),
            "rows": dn,
        }

    def _delta_tombstones_only(self) -> bool:
        """True when this flush can skip the delta re-sort: delta
        ticks armed, a sorted device segment exists and matches the
        log (no new rows since it was built), the only pending work is
        tombstones, their volume is under the rebuild threshold, and
        this backend owns plain single-device segments (the sharded
        backend's replicated shardings keep the full path)."""
        pending = len(self._pending_delta_dead)
        return (
            self._delta_ticks
            and pending > 0
            and self._dn == self._delta_built_n
            and self._delta_buf is not None
            and self._delta_bundle is not None
            and self._delta_scatter_supported()
            and pending <= max(
                1, int(self.delta_rebuild_threshold * self._delta_built_n)
            )
        )

    def _delta_scatter_supported(self) -> bool:
        """Single-chip segments take the in-place sorted scatter; the
        sharded backend overrides to False (replicated shardings)."""
        return True

    def _scatter_sorted_tombstones(self) -> None:
        """O(K) incremental update of the persistent device hash: land
        pending tombstones in BOTH delta twins — the insertion-order
        buffer (so future sorts/compactions see them) and the sorted
        serving segment at host-mapped positions (so this flush ships
        K indices instead of re-sorting the whole log). Keys, run
        remainders and the probe table are untouched — tombstones
        rewrite peers only."""
        t0 = time.perf_counter()
        rows = np.asarray(self._pending_delta_dead, np.int32)
        padded = pad_to(rows, next_pow2(rows.size),
                        np.int32(self._delta_buf_cap))
        self._delta_buf = (
            *self._delta_buf[:2],
            self._scatter_delta_dead(self._delta_buf[2], padded),
        )
        pos = self._delta_sorted_positions()
        sorted_rows = pad_to(
            pos[rows].astype(np.int32), next_pow2(rows.size),
            np.int32(self._delta_buf_cap),
        )
        dev = self._delta_bundle["dev"]
        self._delta_bundle = {
            **self._delta_bundle,
            "dev": (*dev[:2], _scatter_dead(dev[2], sorted_rows), *dev[3:]),
        }
        self._pending_delta_dead.clear()
        self.delta_sync_scatters += 1
        self.last_delta_sync = {
            "path": "scatter",
            "ms": round((time.perf_counter() - t0) * 1e3, 3),
            "rows": int(rows.size),
        }

    def _delta_sorted_positions(self) -> np.ndarray:
        """Host mirror of the device delta sort: log row → position in
        the sorted segment. Both sides run a STABLE ascending sort of
        the identical padded key array (keys never change after
        append), so the permutations agree exactly. Cached per
        (built, cap) build state; any event that rewrites log rows
        (compaction tail shift, clear) resets the cache explicitly."""
        state = (self._delta_built_n, self._delta_buf_cap)
        if self._delta_sort_pos is None or self._delta_sort_pos[0] != state:
            keys = np.full(self._delta_buf_cap, PAD_KEY, np.int64)
            keys[: self._delta_built_n] = self._dk[: self._delta_built_n]
            order = np.argsort(keys, kind="stable")
            pos = np.empty(self._delta_buf_cap, np.int64)
            pos[order] = np.arange(self._delta_buf_cap)
            self._delta_sort_pos = (state, pos)
        return self._delta_sort_pos[1]

    # -- delta device-op seams (sharded backend overrides with
    # replicated shardings) --

    def _alloc_delta_buffer(self, cap: int) -> tuple:
        return _alloc_buffers(cap)

    def _grow_delta_buffer(self, bufs: tuple, cap: int) -> tuple:
        return _grow_buffers(bufs, cap)

    def _write_delta_chunk(self, bufs: tuple, chunk: tuple, start: int):
        return _write_chunk(bufs, chunk, np.int32(start))

    def _scatter_delta_dead(self, peer_buf, rows: np.ndarray):
        return _scatter_dead(peer_buf, rows)

    def _sort_delta(self, bufs: tuple, n_buckets: int) -> tuple:
        return _sort_segment_dev(*bufs, n_buckets=n_buckets)

    def _upload_stale_base(self) -> None:
        """Ship a deferred (host-newer-than-device) base to the device.
        The host arrays already reflect every mutation up to now —
        including tombstones, so the pending scatter list is moot."""
        if not self._base_stale:
            return
        if self._dn:
            # a load phase is ending (stale base = no dispatch since
            # the rebuilds) with a delta tail the fraction threshold
            # didn't catch — live rows, or tombstone-only rows that
            # would still cost a device sort: fold it in now, so the
            # flush ships ONE fully-compacted base instead of also
            # sorting/uploading a delta segment (and compiling its
            # shape tier). The rebuild clears all delta state.
            self._rebuild_base_with(
                np.empty(0, np.int64), np.empty(0, np.int32),
                np.empty((0, 3), np.int64), np.empty(0, np.int64),
            )
        # flag cleared only AFTER the upload: a transient device/link
        # failure here must leave the flush retryable, not permanently
        # drop the base segment from device queries
        self._base_bundle = (
            self._upload_base(self._bk, self._bk2, self._bp, self._base_k)
            if self._bk.size else None
        )
        self._base_stale = False
        self._pending_dead = []

    def _compact_sync(self) -> None:
        if self._compaction is not None:
            self._abandon_compaction()
        self._rebuild_base_with(
            np.empty(0, np.int64), np.empty(0, np.int32),
            np.empty((0, 3), np.int64), np.empty(0, np.int64),
        )
        self.compactions += 1
        # the rebuild marked dirty (and _clear_delta reset all delta
        # state); complete the flush for the new state. This runs
        # INSIDE flush, after its own stale-upload step — the rebuilt
        # base must reach the device before this flush returns.
        self._upload_stale_base()
        self._dirty = False

    def _start_compaction(self) -> None:
        """Fold base + device-resident delta into a fresh base on a
        worker thread. The DEVICE side sorts its own resident arrays —
        zero host→device transfer; the host applies the identical
        stable transform to its numpy mirror so row indices stay
        aligned. Must run right after ``_sync_delta`` (flush order), so
        device state == host state up to ``_delta_built_n``."""
        consumed = self._delta_built_n
        snap = {
            "bk": self._bk, "bk2": self._bk2, "bw": self._bw,
            "bxyz": self._bxyz, "bp": self._bp.copy(),
            "dk": self._dk[:consumed].copy(),
            "dk2": self._dk2[:consumed].copy(),
            "dw": self._dw[:consumed].copy(),
            "dxyz": self._dxyz[:consumed].copy(),
            "dp": self._dp[:consumed].copy(),
            "delta_cap": self._delta_buf_cap,
            "base_bundle": self._base_bundle,
            "delta_buf": self._delta_buf,
        }
        state = {
            "done": threading.Event(),
            "epoch": self._epoch,
            "consumed_dn": consumed,
            "started": time.monotonic(),
            "result": None,
            "error": None,
        }

        def work():
            # done must be set on EVERY exit: an unset event would wedge
            # wait_compaction forever and block future compactions (the
            # guard requires _compaction is None).
            try:
                state["result"] = self._compact_work(snap)
            except BaseException as exc:  # noqa: BLE001 — surfaced at swap
                state["error"] = exc
            finally:
                state["done"].set()

        state["thread"] = threading.Thread(
            target=work, name="index-compaction", daemon=True
        )
        self._compaction = state
        self._replay = []
        state["thread"].start()

    def _compact_work(self, snap: dict) -> tuple:
        """Build the compacted base: host mirror (numpy) + device twin.
        Runs off the owning thread; touches only the snapshot."""
        # host mirror: full-capacity views matching the device layout
        dcap = snap["delta_cap"]
        dk = pad_to(snap["dk"], dcap, PAD_KEY)
        dk2 = pad_to(snap["dk2"], dcap, np.int64(0))
        dw = pad_to(snap["dw"], dcap, NO_WORLD)
        dxyz = pad_to(snap["dxyz"], dcap, _XYZ_PAD)
        dp = pad_to(snap["dp"], dcap, np.int32(-1))
        keys = np.concatenate([snap["bk"], dk])
        keys2 = np.concatenate([snap["bk2"], dk2])
        wids = np.concatenate([snap["bw"], dw])
        xyz = np.concatenate([snap["bxyz"], dxyz])
        peers = np.concatenate([snap["bp"], dp])
        keys = np.where(peers < 0, PAD_KEY, keys)
        live_total = int((peers >= 0).sum())
        if live_total == 0:
            return (np.empty(0, np.int64), np.empty(0, np.int64),
                    np.empty(0, np.int32), np.empty((0, 3), np.int64),
                    np.empty(0, np.int32), 1, None, 0)
        cap2 = next_pow2(live_total)
        order = np.argsort(keys, kind="stable")[:cap2]
        hk, hk2, hw, hx, hp = (keys[order], keys2[order], wids[order],
                               xyz[order], peers[order])
        k = next_pow2(_max_run(hk[:live_total]), 8)
        bundle = self._compact_device(
            snap, cap2, (hk, hk2, hp), k,
            probe_buckets_for(n_distinct(hk[:live_total])),
        )
        return (hk, hk2, hw, hx, hp, k, bundle, live_total)

    def _compact_device(
        self, snap: dict, cap2: int, host_arrays, k, n_buckets: int
    ) -> dict:
        """Device side of compaction. Single-chip: fold the resident
        arrays in place (no transfer). Falls back to uploading the host
        mirror when base or delta has no device twin yet."""
        base = snap["base_bundle"]
        dbuf = snap["delta_buf"]
        if base is not None:
            bk, bk2, bp = base["dev"][:3]
            delta = dbuf if dbuf is not None else _alloc_buffers(8)
            dev = _device_compact(
                bk, bk2, bp, *delta, cap2=cap2, n_buckets=n_buckets
            )
            return {"dev": dev, "cap": cap2}
        return self._upload_base(*host_arrays, k)

    def wait_compaction(self) -> None:
        """Block until no compaction is in flight (tests, benchmarks,
        shutdown). The post-swap flush may start a follow-up compaction
        over the delta tail; loop until quiescent. A failed compaction
        raises here (a silent retry could spin this loop forever), and
        so does a wedged one — an unbounded wait would hang shutdown."""
        while self._compaction is not None:
            if not self._compaction["done"].wait(self.COMPACT_STALL_SECS):
                self._abandon_compaction()
                self.compaction_failures += 1
                self._failed_streak += 1
                raise RuntimeError(
                    "compaction wedged: no progress within "
                    f"{self.COMPACT_STALL_SECS}s"
                )
            err = self._swap_compaction()
            if err is not None:
                raise RuntimeError("background compaction failed") from err
            self._dirty = True
            self.flush()

    def _swap_compaction(self) -> BaseException | None:
        """Install a finished compaction; returns the worker's error, if
        any. On failure the host authority is untouched (the worker only
        reads its snapshot), so recovery is: drop the attempt and let
        the flush policy retry in the background — a persistent failure
        eventually overruns the delta and surfaces synchronously on the
        owning thread via ``_compact_sync``."""
        state = self._compaction
        self._compaction = None
        if state["epoch"] != self._epoch:
            return None  # a reseed/sync rebuild superseded this run
        if state["error"] is not None:
            self._replay = []
            self.compaction_failures += 1
            self._failed_streak += 1
            # Re-arm the flush policy step: with no new mutations an
            # un-dirty flush would early-return and never retry.
            self._dirty = True
            return state["error"]
        keys, keys2, wids, xyz, pids, k, bundle, live_total = state["result"]
        self._failed_streak = 0
        self._bk, self._bk2 = keys, keys2
        self._bw, self._bxyz, self._bp = wids, xyz, pids
        self._base_pid_order = None
        self._base_k = k
        self._base_bundle = bundle
        self._base_live = live_total
        self._base_dead = 0
        self._pending_dead = []
        self.compactions += 1

        # replay removals that touched snapshot rows
        if self._replay:
            for key, pid in self._replay:
                lo, hi = self._base_run(key)
                j = np.flatnonzero(self._bp[lo:hi] == pid)
                if j.size:
                    row = lo + int(j[0])
                    self._bp[row] = -1
                    self._pending_dead.append(row)
                    self._base_dead += 1
                    self._base_live -= 1
            self._replay = []

        # shift the unconsumed delta tail to the front; the device
        # buffer restarts from scratch (the tail is small — rows added
        # while the compaction ran)
        consumed = state["consumed_dn"]
        rem = self._dn - consumed
        if rem:
            self._dk[:rem] = self._dk[consumed:self._dn]
            self._dw[:rem] = self._dw[consumed:self._dn]
            self._dxyz[:rem] = self._dxyz[consumed:self._dn]
            self._dp[:rem] = self._dp[consumed:self._dn]
        self._dn = rem
        self._delta_live = int((self._dp[:rem] >= 0).sum())
        self._delta_index = {
            (int(self._dk[r]), int(self._dp[r])): r
            for r in range(rem) if self._dp[r] >= 0
        }
        keyrow: dict[int, int] = {}
        kc: Counter = Counter()
        pid_rows: dict[int, list[int]] = {}
        for r in range(rem):
            key = int(self._dk[r])
            keyrow.setdefault(key, r)
            kc[key] += 1
            pid = int(self._dp[r])
            if pid >= 0:
                pid_rows.setdefault(pid, []).append(r)
        self._delta_keyrow = keyrow
        self._delta_key_count = kc
        self._delta_pid_rows = pid_rows
        self._delta_max_run = max(kc.values(), default=1)
        self._delta_buf = None
        self._delta_buf_cap = 0
        self._delta_built_n = 0
        self._pending_delta_dead = []
        self._delta_bundle = None
        self._delta_sort_pos = None  # log rows shifted — stale mapping
        self._delta_stale = True
        self._dirty = True

    def _abandon_compaction(self) -> None:
        """Invalidate an in-flight compaction (reseed/sync rebuild is
        about to replace the base wholesale)."""
        self._epoch += 1
        self._compaction = None
        self._replay = []

    def _install_base(self, keys, wids, xyz, pids) -> None:
        """Install a freshly sorted base from live rows (bulk load /
        reseed), padding host arrays to the device capacity so host row
        indices always mirror the device layout."""
        self._epoch += 1
        if self._delta_ticks:
            # wholesale membership/key rewrite: nothing cached before
            # this instant may ever replay (reseed changes every key;
            # a bulk fold can carry rows the churn stream never marked)
            self._coherence.invalidate_all()
        n = int(keys.size)
        self._base_pid_order = None
        # any successful base install (bulk fold, reseed, sync fold)
        # proves the path healthy again — a stale failure streak must
        # not force future overruns onto the owning thread
        self._failed_streak = 0
        self._base_live = n
        self._base_dead = 0
        self._base_k = next_pow2(_max_run(keys), 8) if n else 1
        if n:
            cap = next_pow2(n)
            self._bk = pad_to(keys, cap, PAD_KEY)
            self._bk2 = pad_to(
                spatial_keys2(
                    wids.astype(np.int32, copy=False), xyz, self._seed
                ),
                cap, np.int64(0),
            )
            self._bw = pad_to(wids.astype(np.int32, copy=False), cap, NO_WORLD)
            self._bxyz = pad_to(xyz, cap, _XYZ_PAD)
            self._bp = pad_to(pids.astype(np.int32, copy=False), cap,
                              np.int32(-1))
            # upload DEFERRED to the next flush: consecutive bulk loads
            # (per-world build calls, snapshot restore) re-install the
            # base once per call but ship it to the device once total
            self._base_bundle = None
            self._base_stale = True
        else:
            self._bk = np.empty(0, np.int64)
            self._bk2 = np.empty(0, np.int64)
            self._bw = np.empty(0, np.int32)
            self._bxyz = np.empty((0, 3), np.int64)
            self._bp = np.empty(0, np.int32)
            self._base_bundle = None
            self._base_stale = False
        self._pending_dead = []
        self._replay = []

    def _clear_delta(self) -> None:
        self._delta_sort_pos = None
        self._dn = 0
        self._delta_live = 0
        self._delta_index = {}
        self._delta_keyrow = {}
        self._delta_key_count = Counter()
        self._delta_max_run = 1
        self._delta_pid_rows = {}
        self._delta_buf = None
        self._delta_buf_cap = 0
        self._delta_built_n = 0
        self._pending_delta_dead = []
        self._delta_bundle = None
        self._delta_stale = False

    # endregion

    # region: device upload seams (overridden by the sharded backend)

    def _upload_base(self, keys, keys2, pids, k) -> dict:
        cap = next_pow2(keys.size)
        padded_keys = pad_to(keys, cap, PAD_KEY)
        sk = jnp.asarray(padded_keys)
        sk2 = jnp.asarray(pad_to(keys2, cap, np.int64(0)))
        rem = jnp.asarray(run_remainders_np(padded_keys))
        tbl, oflow = _probe_only_dev(
            sk, sk2, n_buckets=probe_buckets_for(n_distinct(keys))
        )
        return {
            "dev": (
                sk,
                sk2,
                jnp.asarray(pad_to(pids.astype(np.int32), cap, np.int32(-1))),
                rem, tbl, oflow,
            ),
            "cap": cap,
        }

    def _scatter_base_dead(self, bundle: dict, rows: np.ndarray) -> dict:
        # tombstones rewrite peers only — keys, runs and the probe
        # table stay valid for the segment's lifetime
        dev = bundle["dev"]
        cap = bundle["cap"]
        padded = pad_to(rows, next_pow2(rows.size), np.int32(cap))
        return {
            **bundle,
            "dev": (*dev[:2], _scatter_dead(dev[2], padded), *dev[3:]),
        }

    # endregion

    # region: batched hot path

    def _segments(self):
        """→ (device array tuples, K per segment, segment kinds). Kinds
        matter to the sharded backend: the base is space-sharded, the
        delta replicated."""
        segs, ks, kinds = [], [], []
        if self._base_bundle is not None:
            segs.append(self._base_bundle["dev"])
            ks.append(self._base_k)
            kinds.append("base")
        if self._delta_bundle is not None:
            segs.append(self._delta_bundle["dev"])
            ks.append(self._delta_k)
            kinds.append("delta")
        return segs, tuple(ks), tuple(kinds)

    def match_arrays(
        self,
        world_ids: np.ndarray,
        positions: np.ndarray,
        sender_ids: np.ndarray,
        repls: np.ndarray,
    ) -> np.ndarray:
        """Array-native hot path: [M] int32 interned world ids, [M, 3]
        f64 positions, [M] int32 sender peer ids (-1 for none), [M] int8
        replication → [M, K] int32 peer ids, -1-padded.

        Quantizes host-side (golden f64 semantics), then one fused
        device batch. The object API wraps this; benchmarks call it
        directly.
        """
        m, result = self.match_arrays_async(
            world_ids, positions, sender_ids, repls
        )
        if result is None:
            return np.full((m, 1), -1, dtype=np.int32)
        # Convert the whole (prefetched) array, trim on host — a device
        # slice would dispatch again and re-transfer. This sync IS the
        # synchronous API's contract.
        return np.asarray(result)[:m]  # wql: allow(jax-host-sync, full-fetch-on-tick) — the sync API's contract

    def match_arrays_async(
        self,
        world_ids: np.ndarray,
        positions: np.ndarray,
        sender_ids: np.ndarray,
        repls: np.ndarray,
        max_hits: int | None = None,
        csr_cap: int | None = None,
    ):
        """Asynchronous hot path: dispatch without forcing the result.

        Returns ``(m, result)`` where ``result`` is the device value —
        dense ``targets``; with ``max_hits`` the sparse
        ``(rows, targets, n_hits)`` triple; with ``csr_cap`` the
        compacted ``(counts, flat_targets, total)`` triple. Callers
        overlap ticks by dispatching tick t+1 before reading tick t
        (double buffering: transfer and compute of adjacent ticks
        overlap)."""
        self.flush()
        m = len(world_ids)
        segs, ks, kinds = self._segments()
        if not segs or m == 0:
            return m, None

        queries = self._prepare_queries(
            world_ids, positions, sender_ids, repls
        )
        result = self._launch(
            queries, segs, ks, kinds, csr_cap=csr_cap, max_hits=max_hits
        )
        return m, result[0] if max_hits is None and csr_cap is None else result

    def _launch(self, queries, segs, ks, kinds, *, csr_cap=None,
                max_hits=None):
        """Pick the result layout, dispatch, and enqueue the D2H
        prefetch (by the time a pipelined caller reads, the copy has
        landed — the read costs no round-trip). Returns a tuple of
        device arrays. Shared by the array API and the server delivery
        path so the dispatch pipeline cannot drift between them."""
        if csr_cap is not None:
            # zone A needs one identity row per (padded query, segment)
            csr_cap = max(
                csr_cap, CSR_ROW * queries[0].shape[0] * len(segs) + 64
            )
            result = self._dispatch_csr(
                queries, segs, ks, kinds,
                self._csr_effective_cap(next_pow2(csr_cap), queries, segs),
            )
        elif max_hits is not None:
            result = self._dispatch_sparse(
                queries, segs, ks, kinds, next_pow2(max_hits)
            )
        else:
            result = (self._dispatch(queries, segs, ks, kinds),)
        prefetch = result
        if csr_cap is not None and self._compact_applicable(csr_cap):
            # counts + total only: the cap-padded flat stays on device —
            # collect packs it into a bucket sized to the ACTUAL fan-out
            # and fetches that instead (prefetching the full array here
            # would ship the O(cap) bytes the compaction exists to
            # avoid)
            prefetch = (result[0], result[2])
        t_pf = time.perf_counter()
        for r in prefetch:
            copy = getattr(r, "copy_to_host_async", None)
            if copy is not None:
                copy()
        # D2H-prefetch enqueue wall, folded into the device timing
        # split by dispatch_local_batch (the enqueue is async — the
        # transfer itself lands inside the collect-side fetch wall)
        self._last_prefetch_ms = (time.perf_counter() - t_pf) * 1e3
        return result

    def _query_cap(self, m: int) -> int:
        """Padded query-batch capacity tier; sharded backends round to
        their batch-axis divisibility."""
        return next_pow2(m)

    def _prepare_queries(self, world_ids, positions, sender_ids, repls):
        """Quantize + hash + pad one query batch into the device query
        tuple. 21 B/query on the wire (two keys + sender + replication)
        — the raw (world, cube) identity stays on the host. Quantize,
        both hashes AND the capacity-tier padding of all four columns
        run as one fused GIL-releasing native pass when the C++ kernel
        is built (spatial/native_keys.py wql_encode_queries; the
        composed query_keys + pad_to path otherwise, bit-identical)."""
        cap = self._query_cap(len(world_ids))
        return encode_queries(
            world_ids, positions, sender_ids, repls, cap,
            self.cube_size, self._seed,
        )

    def _dispatch(self, queries: tuple, segs, ks, kinds):
        """Run the padded query arrays against the device segments.
        Numpy args go straight into the jitted call so all H2D
        transfers ride one dispatch — on tunneled/remote devices
        per-array ``device_put`` round-trips dominate otherwise."""
        flat = [a for seg in segs for a in seg]
        return _match_dense_kernel(*flat, *queries, ks=ks)

    def _dispatch_sparse(self, queries: tuple, segs, ks, kinds, c: int):
        flat = [a for seg in segs for a in seg]
        return _match_sparse_kernel(*flat, *queries, ks=ks, c=c)

    def _dispatch_csr(self, queries: tuple, segs, ks, kinds, t_cap: int):
        flat = [a for seg in segs for a in seg]
        return _match_run_csr_kernel(
            *flat, *queries, nseg=len(segs), t_cap=t_cap
        )

    def _csr_effective_cap(self, t_cap: int, queries: tuple, segs) -> int:
        """The slot capacity the CSR kernel will REALLY run with at a
        requested ``t_cap``. Subclasses raise it (per-shard region
        floors); idempotent. Every caller that records a cap for the
        overflow-sentinel test (collect_local_batch) must record this
        value: if the kernel's true cap were higher than the recorded
        one, totals between the two would look like overflow and take
        a spurious dense re-resolve every tick (ADVICE r5)."""
        return t_cap

    def match_local_batch(
        self, queries: Sequence[LocalQuery]
    ) -> list[list[uuid_mod.UUID]]:
        return self.collect_local_batch(self.dispatch_local_batch(queries))

    def dispatch_local_batch(self, queries: Sequence[LocalQuery]):
        """Encode + launch a query batch without waiting for results.

        This is the OBJECT-LIST path: it re-walks every LocalQuery in
        Python (interning dict probes, row-by-row position fills) —
        the staged columnar path (:meth:`dispatch_staged_batch`) moves
        that work to message-arrival time and is what the ticker uses
        when staging is on; this path remains for the CPU-compat API,
        immediate mode, and staging-desync fallbacks.

        Runs on the owning (event-loop) thread — it reads the interning
        dicts, which mutate there. The returned handle goes to
        ``collect_local_batch``, which only blocks on the device and may
        safely run on a worker thread (tick batcher overlap).
        """
        m = len(queries)
        if m == 0:
            return (0, None, {})
        t_start = time.perf_counter()
        world_ids = np.fromiter(
            (self._world_ids.get(q.world, -1) for q in queries),  # wql: allow(per-query-python-loop) — the legacy list-path encode
            dtype=np.int32, count=m,
        )
        positions = np.empty((m, 3), dtype=np.float64)
        for i, q in enumerate(queries):  # wql: allow(per-query-python-loop) — the legacy list-path encode
            positions[i] = (q.position.x, q.position.y, q.position.z)
        sender_ids = np.fromiter(
            (self._peer_ids.get(q.sender, -1) for q in queries),  # wql: allow(per-query-python-loop) — the legacy list-path encode
            dtype=np.int32, count=m,
        )
        repls = np.fromiter(
            (int(q.replication) for q in queries), dtype=np.int8, count=m  # wql: allow(per-query-python-loop) — the legacy list-path encode
        )
        if any(q.kind for q in queries):  # wql: allow(per-query-python-loop) — the legacy list-path encode
            kind_col = np.fromiter(
                (q.kind for q in queries), dtype=np.int8, count=m  # wql: allow(per-query-python-loop) — the legacy list-path encode
            )
            par_col = np.zeros((m, _QUERY_PARAM_LANES), np.float64)
            for i, q in enumerate(queries):  # wql: allow(per-query-python-loop) — the legacy list-path encode
                if q.params:
                    par_col[i, : len(q.params)] = q.params
            self.list_dispatches += 1
            return self._dispatch_kind_batch(
                world_ids, positions, sender_ids, repls,
                kind_col, par_col, staged=False,
            )
        self.list_dispatches += 1
        if self._delta_ticks:
            # object-list dispatches (staging desync, CPU-compat API)
            # bypass the reuse cache: count the fallback so a serving
            # path stuck off staging is visible in the delta stats
            self.delta_fallbacks += 1
            self.last_delta_stats = {
                "batch": m, "reused": 0, "recomputed": m,
                "churn_rows": self._coherence.take_window_marks(),
                "dirty_cubes": len(self._coherence.dirty),
                "fallback": "list_path",
            }
        return self._dispatch_encoded(
            m, world_ids, positions, sender_ids, repls, t_start,
            staged=False,
        )

    def dispatch_staged_batch(
        self, world_ids, positions, sender_ids, repls,
        kinds=None, params=None, fallback=None,
    ):
        """Launch a batch straight from the ticker's staged columnar
        arrays — world/peer interning already happened at enqueue time
        (engine/staging.py), so this is zero per-query Python: one
        fused vectorized encode (native when built) and the launch.
        A batch carrying non-radius ``kinds`` lanes routes through the
        query-library probe expansion first; ``None`` or an all-zero
        kind column is the pure-radius pipeline, byte for byte.
        ``fallback`` is ignored here (see robustness/resilient.py)."""
        m = len(world_ids)
        if m == 0:
            return (0, None, {})
        if kinds is not None and np.any(kinds):
            return self._dispatch_kind_batch(
                world_ids, positions, sender_ids, repls,
                kinds, params, staged=True,
            )
        t_start = time.perf_counter()
        self.staged_dispatches += 1
        if self._delta_ticks:
            return self._dispatch_delta(
                m, world_ids, positions, sender_ids, repls, t_start
            )
        return self._dispatch_encoded(
            m, world_ids, positions, sender_ids, repls, t_start,
            staged=True,
        )

    def _dispatch_kind_batch(
        self, world_ids, positions, sender_ids, repls, kinds, params,
        *, staged: bool,
    ):
        """Kind-dispatched leg of both dispatch paths: expand the mixed
        batch into pure-radius probe rows (queries/expand.py) — device
        stencil kernels pick the candidate cubes per kind — then send
        the probes through the NORMAL staged pipeline against the same
        persistent index (same CSR delivery, same capacity tiers, and
        delta-tick reuse at probe granularity: probes are
        content-addressed rows, so a repeated cone replays its cached
        cubes). Collect sees a ``("qk", plan, inner)`` handle and folds
        the per-probe fan-outs back into one result per query."""
        from ..queries.expand import expand_staged

        m = len(world_ids)
        plan, p_wid, p_pos, p_sid, p_repl = expand_staged(
            world_ids, positions, sender_ids, repls, kinds, params,
            cube_size=self.cube_size,
            stencil_max=self.query_stencil_max,
            ray_steps_max=self.query_ray_steps,
        )
        self.kind_expansions += 1
        if staged:
            inner = self.dispatch_staged_batch(p_wid, p_pos, p_sid, p_repl)
        else:
            inner = self._dispatch_encoded(
                len(p_wid), p_wid, p_pos, p_sid, p_repl,
                time.perf_counter(), staged=False,
            )
        return (m, ("qk", plan, inner), inner[2])

    def _dispatch_delta(
        self, m, world_ids, positions, sender_ids, repls, t_start,
    ):
        """Temporal-coherence dispatch (delta ticks armed): partition
        the staged batch by the reuse cache — rows whose content
        signature matches a cached entry with a clean cube replay that
        entry's fan-out; only the DIRTY rows enter the device batch,
        at their own (smaller) capacity tier. The handle carries the
        replayed rows and the compute sub-batch; collect merges them
        back in query order and refreshes the cache."""
        co = self._coherence
        h1, h2 = row_signatures(world_ids, positions, sender_ids, repls)
        h1_list = h1.tolist()
        h2_list = h2.tolist()
        reused, dirty_rows = co.partition(h1_list, h2_list)
        n_dirty = len(dirty_rows)
        self.delta_reused += m - n_dirty
        self.delta_recomputed += n_dirty
        self.last_delta_stats = {
            "batch": m,
            "reused": m - n_dirty,
            "recomputed": n_dirty,
            "churn_rows": co.take_window_marks(),
            "dirty_cubes": len(co.dirty),
            "fallback": "",
        }
        seq_now = co.seq
        if n_dirty == 0:
            # every row replayed: no device work at all this tick
            self.flush()  # index mutations still owe their device sync
            self.last_device_timing = {
                "encode_ms": (time.perf_counter() - t_start) * 1e3,
                "h2d_ms": 0.0, "d2h_enqueue_ms": 0.0,
                "compute_ms": 0.0, "d2h_ms": 0.0,
                "path": "reuse", "staged": True, "query_cap": 0,
            }
            return (m, ("tc", reused, None, None, (), (), (), seq_now),
                    dict(self.last_device_timing))
        if n_dirty == m:
            # cold cache / all-dirty: dispatch the batch unsplit (no
            # gather cost) but still record results for future reuse
            dkeys, _ = query_keys(
                world_ids, positions, self.cube_size, self._seed
            )
            inner = self._dispatch_encoded(
                m, world_ids, positions, sender_ids, repls, t_start,
                staged=True,
            )
            return (inner[0], ("tc", reused, None, inner,
                               h1_list, h2_list, dkeys.tolist(), seq_now),
                    inner[2])
        idx = np.asarray(dirty_rows, np.intp)
        sub_wid = world_ids[idx]
        sub_pos = np.ascontiguousarray(positions[idx])
        sub_sid = sender_ids[idx]
        sub_repl = repls[idx]
        dkeys, _ = query_keys(sub_wid, sub_pos, self.cube_size, self._seed)
        inner = self._dispatch_encoded(
            n_dirty, sub_wid, sub_pos, sub_sid, sub_repl, t_start,
            staged=True, delta_sub=True,
        )
        return (m, ("tc", reused, idx, inner,
                    [h1_list[i] for i in dirty_rows],
                    [h2_list[i] for i in dirty_rows],
                    dkeys.tolist(), seq_now),
                inner[2])

    def _collect_delta(self, m, payload) -> list[list[uuid_mod.UUID]]:
        """Collect half of :meth:`_dispatch_delta`: wait out the dirty
        sub-batch (if any), splice replayed rows back in query order,
        and insert the recomputed fan-outs into the reuse cache under
        the dispatch-time sequence snapshot. Runs on the collect
        worker thread — cache inserts are single dict stores with
        immutable values (see delta_ticks.py threading note)."""
        _, reused, idx, inner, dh1, dh2, dkeys, seq_now = payload
        if inner is None:
            return reused
        sub = self.collect_local_batch(inner)
        co = self._coherence
        if idx is None:  # all-dirty: sub IS the batch, in order
            for j, targets in enumerate(sub):
                co.store(dh1[j], dh2[j], dkeys[j], seq_now, targets)
            return sub
        out = reused
        for j, i in enumerate(idx.tolist()):
            out[i] = sub[j]
            co.store(dh1[j], dh2[j], dkeys[j], seq_now, sub[j])
        return out

    def _dispatch_encoded(
        self, m, world_ids, positions, sender_ids, repls, t_start,
        *, staged: bool, delta_sub: bool = False,
    ):
        """Shared launch tail of both dispatch paths: flush, quantize/
        hash/pad, pick the result layout, launch, enqueue the D2H
        prefetch. Returns the ``(m, payload, timing)`` handle.
        ``delta_sub`` marks a delta-tick dirty partition: it sizes the
        CSR result off (and adapts) the sub-path's own capacity hint
        instead of the full-tick one."""
        self.flush()
        segs, ks, kinds = self._segments()
        if not segs:
            return (m, None, {})
        qtuple = self._prepare_queries(
            world_ids, positions, sender_ids, repls
        )
        # host-encode wall: quantize/hash/pad (+ the object-list
        # interning loops when staged is False; index flush included —
        # it runs on this thread either way)
        t_encoded = time.perf_counter()
        # CSR delivery: the result ships ~total ints instead of a dense
        # [M, K] table (K is set by the hottest cube). The capacity
        # hint adapts to the observed fan-out. m * sum(K) is the true
        # fan-out ceiling: once the hint reaches it, CSR saves nothing
        # over dense — and dispatching dense there also guarantees a
        # persistent overflow (e.g. overflow-tier exhaustion at a
        # clamped t_cap) always escapes instead of re-dispatching
        # forever.
        ceiling = next_pow2(m * sum(ks))
        hint = (
            self._delta_delivery_cap if delta_sub else self._delivery_cap
        )
        t_cap = self._csr_effective_cap(next_pow2(max(
            hint,
            # zone-A floor: one identity row per (padded query, segment)
            CSR_ROW * self._query_cap(m) * len(segs) + 64,
        )), qtuple, segs)
        self.last_dispatch_tier = {
            "t_cap": t_cap, "query_cap": self._query_cap(m),
            "segments": len(segs),
        }
        if t_cap >= ceiling:
            (tgt,) = self._launch(qtuple, segs, ks, kinds)
            timing = self._dispatch_timing(
                t_start, t_encoded, path="dense", staged=staged, m=m,
                delta_sub=delta_sub,
            )
            return (m, ("dense", tgt), timing)
        result = self._launch(qtuple, segs, ks, kinds, csr_cap=t_cap)
        timing = self._dispatch_timing(
            t_start, t_encoded, path="csr", staged=staged, m=m,
            delta_sub=delta_sub,
        )
        return (m, ("csr", t_cap, result, (qtuple, segs, ks, kinds)),
                timing)

    def _dispatch_timing(self, t_start: float, t_encoded: float, *,
                         path: str, staged: bool, m: int,
                         delta_sub: bool = False) -> dict:
        """This dispatch's host-side timing legs. The dict RIDES THE
        HANDLE to its own collect — pairing is structural, so an
        errored/dropped collect can never desync attribution at
        pipeline depth > 1 (the old FIFO deque could). ``delta_sub``
        rides along so the collect adapts the right capacity hint."""
        now = time.perf_counter()
        return {
            "encode_ms": (t_encoded - t_start) * 1e3,
            # launch wall: H2D enqueue + kernel dispatch (async on
            # a real device, so this is queue time, not compute)
            "h2d_ms": (now - t_encoded) * 1e3
            - self._last_prefetch_ms,
            "d2h_enqueue_ms": self._last_prefetch_ms,
            "path": path,
            "staged": staged,
            "delta_sub": delta_sub,
            "query_cap": self._query_cap(m),
        }

    def collect_local_batch(self, handle) -> list[list[uuid_mod.UUID]]:
        """Wait for a dispatched batch and decode fan-out UUID lists.
        Safe on a worker thread: peer ids are append-only (index reads
        stay valid), and the overflow fallback re-dispatches the device
        arrays CAPTURED at dispatch time — it never touches host state
        the owning thread could be mutating."""
        m, payload, timing = handle
        if payload is None:
            return [[] for _ in range(m)]
        if payload[0] == "qk":
            # kind-expanded batch: collect the probe fan-outs through
            # whatever path the inner dispatch took (CSR, dense, delta
            # replay), then fold them per original query
            from ..queries.expand import fold_collected

            return fold_collected(
                payload[1], self.collect_local_batch(payload[2])
            )
        if payload[0] == "tc":
            # delta-tick handle: replayed rows + dirty sub-batch; the
            # inner handle (when any) carries its own timing legs
            return self._collect_delta(m, payload)
        # timing rides the handle (see _dispatch_timing): copy before
        # merging so a re-collect of the same handle (drain after a
        # cancelled collect) starts from the dispatch-side legs
        timing = dict(timing)
        if payload[0] == "dense":
            # collect_local_batch IS the tick's designated sync point:
            # it runs on the worker thread while the loop keeps serving
            # transports, so these converts block nothing but the tick.
            t_wait = time.perf_counter()
            tgt = np.asarray(payload[1])[:m]  # wql: allow(jax-host-sync, full-fetch-on-tick) — dense ceiling path
            # dense fetch = one blocking convert: device wait and D2H
            # are indivisible here, so the whole wall lands in
            # compute_ms (tagged by path so readers know)
            timing.update(
                compute_ms=(time.perf_counter() - t_wait) * 1e3,
                d2h_ms=0.0,
            )
            self.last_device_timing = timing
            self._note_fetch(int(tgt.size), 0)
            counts, flat = _dense_to_csr(tgt)
            # the hint must keep adapting here too, or a flash-crowd
            # inflation would park every batch on the dense ceiling
            # path forever
            self._adapt_delivery_cap(
                counts, grow=False,
                delta_sub=bool(timing.get("delta_sub")),
            )
            return self._decode_csr(counts, flat, m)
        _, t_cap, (counts, flat, total), ctx = payload
        delta_sub = bool(timing.get("delta_sub"))
        t_wait = time.perf_counter()
        total = int(total)  # wql: allow(jax-host-sync) — collect point
        # the total is the tick's designated device-wait point: the
        # scalar is only readable once the batch finished, so this
        # wall is the compute leg (plus the link, on tunneled devices)
        timing["compute_ms"] = (time.perf_counter() - t_wait) * 1e3
        if total > t_cap:
            # Rare: the tick's fan-out outgrew the hint — re-resolve
            # dense against the same index snapshot and raise the hint
            # for future ticks. ``total`` is exact unless it is the
            # t_cap+1 layout-overflow sentinel, so convergence is one
            # tick, not log2 doubling steps.
            grown = max(
                t_cap * 2 if total == t_cap + 1
                else next_pow2(2 * total),
                self._delta_delivery_cap if delta_sub
                else self._delivery_cap,
            )
            if delta_sub:
                self._delta_delivery_cap = grown
            else:
                self._delivery_cap = grown
            qtuple, segs, ks, kinds = ctx
            t_fetch = time.perf_counter()
            tgt = np.asarray(  # wql: allow(jax-host-sync, full-fetch-on-tick) — overflow re-resolve
                self._dispatch(qtuple, segs, ks, kinds)
            )[:m]
            timing.update(
                d2h_ms=(time.perf_counter() - t_fetch) * 1e3,
                path="overflow",
            )
            self.last_device_timing = timing
            self._note_fetch(int(tgt.size), 0)
            return self._decode_csr(*_dense_to_csr(tgt), m)
        # counts stays UNTRIMMED: padding queries resolve 0 rows, and
        # the sharded decode needs the full padded layout to locate
        # its per-batch-shard flat regions
        t_fetch = time.perf_counter()
        counts = np.asarray(counts)  # wql: allow(jax-host-sync) — collect
        self._adapt_delivery_cap(counts, grow=True, delta_sub=delta_sub)
        packed = self._compact_fetch(
            payload[2][0], flat, total, t_cap
        )
        if packed is not None:
            timing["d2h_ms"] = (time.perf_counter() - t_fetch) * 1e3
            self.last_device_timing = timing
            return self._decode_packed(counts, packed, m)
        self._note_fetch(t_cap, 0)
        flat_host = np.asarray(flat)  # wql: allow(jax-host-sync, full-fetch-on-tick) — compaction fallback (small tick / no 2x win / shard imbalance)
        timing["d2h_ms"] = (time.perf_counter() - t_fetch) * 1e3
        self.last_device_timing = timing
        return self._decode_csr(counts, flat_host, m)

    def _compact_applicable(self, t_cap: int) -> bool:
        """Whether a tick at this capacity tier is worth compacting:
        below min_cap the dispatch-time full-flat prefetch overlaps
        the link better than a collect-time pack dispatch could."""
        return self.compact_fetch and t_cap >= self.compact_fetch_min_cap

    def _compact_fetch(self, counts, flat, total: int, t_cap: int):
        """On-device compaction of the zoned CSR flat result: pack the
        lanes the decoder will actually read into a power-of-two bucket
        >= ``total`` and fetch ONLY that, so D2H bytes scale with the
        tick's real fan-out instead of the capacity tier. Returns the
        packed host array, or None when the full-fetch fallback applies
        (compaction disabled, small tick, or the bucket would not save
        at least 2x the bytes). ``counts``/``flat`` are the DEVICE
        arrays; ``total`` the already-fetched raw lane total."""
        bucket = next_pow2(max(total, self.compact_min_bucket))
        if not self._compact_applicable(t_cap) or bucket * 2 > t_cap:
            return None
        packed, _ = self._dispatch_pack(counts, flat, bucket)
        out = np.asarray(packed)  # wql: allow(jax-host-sync) — compacted collect point: O(fan-out) bytes
        self._note_fetch(bucket, bucket)
        return out

    def _dispatch_pack(self, counts, flat, bucket: int):
        return _pack_csr_kernel(counts, flat, bucket=bucket)

    def _note_fetch(self, slots: int, bucket: int) -> None:
        """Record what a collect shipped over the link (``bucket`` 0 =
        full fetch). Worker-thread safe: the dict is replaced
        wholesale, never mutated in place."""
        if bucket:
            self.compact_fetches += 1
        else:
            self.full_fetches += 1
        self.last_collect_stats = {
            "fetch_slots": int(slots),
            "fetch_bytes": int(slots) * 4,
            "compaction_bucket": int(bucket),
        }

    def _decode_packed(self, counts, packed, m: int) -> list[list[uuid_mod.UUID]]:
        """Walk a pack_csr result into per-query UUID lists: lanes for
        (q, s) start at the cumsum of the RAW [M, nseg] counts —
        bit-identical output to :meth:`_decode_csr` over the zoned
        layout (pack_csr emits exactly the lanes that walk reads, in
        the same order)."""
        peer_list = self._peer_list
        mq, nseg = counts.shape
        cnt = counts.reshape(-1).astype(np.int64)
        off = np.cumsum(cnt) - cnt
        out: list[list[uuid_mod.UUID]] = []
        for q in range(min(m, mq)):
            lst: list[uuid_mod.UUID] = []
            for s in range(nseg):
                slot = q * nseg + s
                c = int(cnt[slot])
                if c:
                    a = int(off[slot])
                    lst.extend(
                        peer_list[i] for i in packed[a:a + c] if i >= 0
                    )
            out.append(lst)
        return out

    def _adapt_delivery_cap(self, counts: np.ndarray, *, grow: bool,
                            delta_sub: bool = False) -> None:
        """Track the capacity the observed tick actually needed. Grows
        immediately, decays by halves (one flash-crowd tick must not
        inflate every future tick's D2H). Delta sub-batches adapt
        their OWN hint — a dirty partition's tiny footprint must not
        halve the full-tick hint into an overflow retry."""
        # the footprint is the ZONED layout (match_run_csr) for raw
        # [M, nseg] counts, or plain row padding for the dense
        # fallback's exact [M] counts
        if counts.ndim == 2:
            padded = padded_slots(counts)
        else:
            padded = int(
                ((counts + CSR_ROW - 1) // CSR_ROW).sum()
            ) * CSR_ROW
        needed = next_pow2(max(2 * padded, 64))
        attr = "_delta_delivery_cap" if delta_sub else "_delivery_cap"
        cap = getattr(self, attr)
        if needed >= cap:
            if grow:
                setattr(self, attr, needed)
        else:
            setattr(self, attr, max(needed, cap // 2))

    def _decode_csr(self, counts, flat, m: int) -> list[list[uuid_mod.UUID]]:
        """Walk the CSR layout into per-query UUID lists.

        Two layouts share the walk:
        * ``counts.ndim == 2`` — match_run_csr's ZONED layout: RAW
          [M, nseg] run lengths; each (query, segment)'s first
          up-to-8 lanes sit in its zone-A identity row at
          ``(q * nseg + s) * 8``, remainders past lane 8 in q-major
          seg-minor zone-B regions (CSR_ROW_B-lane rows) after
          ``M * 8 * nseg``. The device left ``-1`` holes for
          filtered lanes.
        * ``counts.ndim == 1`` — exact counts from the dense fallback
          (_dense_to_csr): hole-free, plain ``ceil(c/8)*8`` blocks.
        """
        peer_list = self._peer_list
        out: list[list[uuid_mod.UUID]] = []
        if counts.ndim == 1:
            pos = 0
            for c in counts[:m]:
                out.append([peer_list[i] for i in flat[pos:pos + c]])
                pos += (c + CSR_ROW - 1) // CSR_ROW * CSR_ROW
            return out
        mq, nseg = counts.shape
        base = mq * CSR_ROW * nseg
        pos_b = 0
        for q in range(min(m, mq)):
            lst: list[uuid_mod.UUID] = []
            for s in range(nseg):
                cs = int(counts[q, s])
                if not cs:
                    continue
                at = (q * nseg + s) * CSR_ROW
                lst.extend(
                    peer_list[i]
                    for i in flat[at:at + min(cs, CSR_ROW)]
                    if i >= 0
                )
                if cs > CSR_ROW:
                    r = cs - CSR_ROW
                    at = base + pos_b * CSR_ROW_B
                    lst.extend(
                        peer_list[i] for i in flat[at:at + r] if i >= 0
                    )
                    pos_b += (r + CSR_ROW_B - 1) // CSR_ROW_B
            out.append(lst)
        return out

    # endregion

    # region: point queries (host authority)

    def query_cube(self, world: str, pos: Vector3 | Cube) -> set[uuid_mod.UUID]:
        cube = to_cube(pos, self.cube_size)
        wid = self._world_ids.get(world)
        if wid is None:
            return set()
        key = self._key_of(wid, cube)
        out: set[uuid_mod.UUID] = set()
        try:
            lo, hi = self._base_run(key)
            if lo < hi and (
                self._bw[lo] == wid
                and self._bxyz[lo, 0] == cube[0]
                and self._bxyz[lo, 1] == cube[1]
                and self._bxyz[lo, 2] == cube[2]
            ):
                for pid in self._bp[lo:hi]:
                    if pid >= 0:
                        out.add(self._peer_list[pid])
            drow = self._delta_keyrow.get(key)
            if drow is not None and (
                self._dw[drow] == wid
                and not (self._dxyz[drow] != np.asarray(cube)).any()
            ):
                rows = np.flatnonzero(self._dk[:self._dn] == key)
                for r in rows:
                    pid = self._dp[r]
                    if pid >= 0:
                        out.add(self._peer_list[pid])
        except _CollisionError:  # pragma: no cover — defensive
            pass
        return out

    def query_world(self, world: str) -> set[uuid_mod.UUID]:
        wid = self._world_ids.get(world)
        if wid is None:
            return set()
        return {self._peer_list[pid] for pid in self._world_peers[wid]}

    # endregion

    # region: introspection (tests, metrics)

    def world_names(self) -> list[str]:
        return list(self._world_ids.keys())

    def cube_count(self, world: str) -> int:
        wid = self._world_ids.get(world)
        if wid is None:
            return 0
        live_b = (self._bp >= 0) & (self._bw == wid)
        live_d = (self._dp[:self._dn] >= 0) & (self._dw[:self._dn] == wid)
        return int(np.unique(np.concatenate([
            self._bk[live_b], self._dk[:self._dn][live_d]
        ])).size)

    def subscription_count(self) -> int:
        return self._base_live + self._delta_live

    def export_rows(self):
        """Snapshot export (spatial/snapshot.py): live rows, vectorized
        from the host-authority SoA columns."""
        live_b = self._bp >= 0
        dn = self._dn
        live_d = self._dp[:dn] >= 0
        wid = np.concatenate([
            self._bw[live_b], self._dw[:dn][live_d],
        ]).astype(np.int32)
        cube = np.concatenate([
            self._bxyz[live_b], self._dxyz[:dn][live_d],
        ]).astype(np.int64)
        pid = np.concatenate([
            self._bp[live_b], self._dp[:dn][live_d],
        ]).astype(np.int64)
        return list(self._world_ids), self._peer_list, wid, cube, pid

    def device_stats(self) -> dict:
        return {
            "subscriptions": self.subscription_count(),
            "capacity": (
                (0 if self._base_bundle is None else self._base_bundle["cap"])
                + (0 if self._delta_bundle is None
                   else self._delta_bundle["cap"])
            ),
            "max_fanout_k": self._base_k + (
                self._delta_k if self._delta_bundle is not None else 0
            ),
            "worlds": len(self._world_ids),
            "peers": len(self._peer_list),
            "hash_seed": self._seed,
            "dirty": self._dirty,
            "base_rows": int(self._bk.size),
            "base_dead": self._base_dead,
            "delta_rows": self._dn,
            "delta_live": self._delta_live,
            "compactions": self.compactions,
            "compaction_failures": self.compaction_failures,
            "compaction_in_flight": self._compaction is not None,
            "compact_fetches": self.compact_fetches,
            "full_fetches": self.full_fetches,
            "staged_dispatches": self.staged_dispatches,
            "list_dispatches": self.list_dispatches,
            "kind_expansions": self.kind_expansions,
            "last_fetch_bytes": self.last_collect_stats["fetch_bytes"],
            "last_compaction_bucket":
                self.last_collect_stats["compaction_bucket"],
            "delta_ticks": self._delta_ticks,
            "delta_reused": self.delta_reused,
            "delta_recomputed": self.delta_recomputed,
            "delta_fallbacks": self.delta_fallbacks,
            "delta_sync_scatters": self.delta_sync_scatters,
            "delta_sync_sorts": self.delta_sync_sorts,
            "delta_cache_entries": len(self._coherence.cache),
            "delta_cache_resets": self._coherence.cache_resets,
        }

    # endregion


# --------------------------------------------------------------------
# Host helpers
# --------------------------------------------------------------------


def _sort_segment(keys, wids, xyz, pids):
    """Stable key-sort of a row set → contiguous cube runs."""
    order = np.argsort(keys, kind="stable")
    return (
        np.ascontiguousarray(keys[order]),
        np.ascontiguousarray(wids[order].astype(np.int32, copy=False)),
        np.ascontiguousarray(xyz[order]),
        np.ascontiguousarray(pids[order].astype(np.int32, copy=False)),
    )


def _dense_to_csr(tgt: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized compaction of a dense [M, K] host table to the
    row-padded CSR layout (_decode_csr's contract) — touches only the
    real hits, not M*K cells."""
    mask = tgt >= 0
    counts = mask.sum(axis=1).astype(np.int32)
    prows = (counts + CSR_ROW - 1) // CSR_ROW
    starts = (np.cumsum(prows) - prows) * CSR_ROW
    flat = np.full(int(prows.sum()) * CSR_ROW, -1, np.int32)
    rows = np.nonzero(mask)[0]
    within = (np.cumsum(mask, axis=1) - 1)[mask]
    flat[starts[rows] + within] = tgt[mask]
    return counts, flat


def run_remainders_np(sorted_keys: np.ndarray) -> np.ndarray:
    """Host twin of :func:`run_remainders` (same [S] i32 contract)."""
    s = sorted_keys.size
    if s == 0:
        return np.empty(0, np.int32)
    idx = np.arange(s, dtype=np.int32)
    last = np.empty(s, bool)
    last[:-1] = sorted_keys[1:] != sorted_keys[:-1]
    last[-1] = True
    ends = np.minimum.accumulate(
        np.where(last, idx, np.int32(s - 1))[::-1]
    )[::-1]
    return (ends + 1 - idx).astype(np.int32)


def _max_run(sorted_keys: np.ndarray) -> int:
    """Longest equal-key run in a sorted key array (max cube occupancy
    → the gather degree K)."""
    n = sorted_keys.size
    if n == 0:
        return 1
    starts = np.flatnonzero(np.diff(sorted_keys) != 0) + 1
    bounds = np.concatenate([[0], starts, [n]])
    return int(np.diff(bounds).max())
