"""64-bit spatial keys: (world_id, cube) → one sortable int64.

The device index orders subscriptions by a single scalar key so range
lookups are two ``searchsorted`` binary searches. A cube identity is
128+ bits (world i32 + three i64 cube coords), so the key is a seeded
splitmix64-style hash. Exactness is preserved:

* at flush time the host checks that distinct cubes got distinct keys
  and rehashes with the next seed on collision (expected ~never:
  ~C²/2⁶⁴), so stored cells are injective per epoch;
* every query carries a SECOND independent 64-bit key
  (:func:`spatial_keys2`) that the device compares against the
  candidate run's stored second key. A query for an absent cube is
  mis-routed only if it collides with a stored cube under BOTH hashes
  (~2⁻¹²⁸ per pair — beyond cosmic-ray territory). Shipping 16 key
  bytes instead of the raw 28-byte (world, cube) identity halves the
  per-query transfer and the device index row width — host↔device
  bandwidth is the fan-out engine's scaling limit, not FLOPs.

All functions are vectorized numpy over uint64 with wrapping overflow —
the hot encode path runs at memory bandwidth.
"""

from __future__ import annotations

import numpy as np

# splitmix64 constants — shared with the device twin
# (ops/tick.device_spatial_keys), which must stay bit-identical.
MIX_M1 = 0xBF58476D1CE4E5B9
MIX_M2 = 0x94D049BB133111EB
MIX_GOLDEN = 0x9E3779B97F4A7C15

_M1 = np.uint64(MIX_M1)
_M2 = np.uint64(MIX_M2)
_GOLDEN = np.uint64(MIX_GOLDEN)

# Padding rows sort after every real key; flush re-seeds if a real key
# ever hashes to this value.
PAD_KEY = np.int64(2**63 - 1)
# World-id sentinel that never matches a real (>= 0) interned world.
NO_WORLD = np.int32(-1)
# Seed-space offset separating the two hash families.
KEY2_OFFSET = 0x5851F42D4C957F2D
# Index padding rows pad key2 with 0; padded QUERIES pad with 1, so a
# padding query probing a segment's padding run (both share PAD_KEY)
# fails the second-key exactness check and counts as an empty run —
# without this, padding queries would register as hot-run overflows in
# the two-tier CSR kernel. (A real query whose key2 happens to be 1 is
# fine: matches still require key1 equality, and padding rows carry
# peer -1 anyway.)
QUERY_PAD_KEY2 = np.int64(1)


def _mix(x: np.ndarray) -> np.ndarray:
    x = (x ^ (x >> np.uint64(30))) * _M1
    x = (x ^ (x >> np.uint64(27))) * _M2
    return x ^ (x >> np.uint64(31))


def spatial_keys(
    world_ids: np.ndarray, cubes: np.ndarray, seed: int = 0
) -> np.ndarray:
    """[N] int32 world ids + [N, 3] int64 cube coords → [N] int64 keys."""
    with np.errstate(over="ignore"):
        h = _mix(np.uint64(seed) + _GOLDEN)
        h = _mix(h ^ world_ids.astype(np.int64).view(np.uint64))
        h = _mix(h ^ cubes[..., 0].view(np.uint64))
        h = _mix(h ^ cubes[..., 1].view(np.uint64))
        h = _mix(h ^ cubes[..., 2].view(np.uint64))
    return h.view(np.int64)


def spatial_keys2(
    world_ids: np.ndarray, cubes: np.ndarray, seed: int = 0
) -> np.ndarray:
    """Second, independent key family (same mixer, disjoint seed
    space): the device-side exactness check compares this instead of
    the raw (world, cube) tuple."""
    return spatial_keys(world_ids, cubes, (seed + KEY2_OFFSET) & (2**64 - 1))


def n_distinct(sorted_keys: np.ndarray) -> int:
    """Distinct values in a SORTED key array (>= 1 by convention, so
    probe-table sizing never degenerates to zero buckets). Sizing
    contract partner of tpu_backend.probe_buckets_for — every segment
    build site must count cubes the same way."""
    if sorted_keys.size == 0:
        return 1
    return 1 + int(np.count_nonzero(sorted_keys[1:] != sorted_keys[:-1]))


def next_pow2(n: int, floor: int = 8) -> int:
    """Capacity tier: smallest power of two >= max(n, floor). Bounds
    the number of distinct compiled shapes to log2(capacity)."""
    n = max(n, floor)
    return 1 << (n - 1).bit_length()


def pad_to(arr: np.ndarray, size: int, fill) -> np.ndarray:
    """Pad ``arr`` along axis 0 to ``size`` rows with ``fill``."""
    pad = size - arr.shape[0]
    if pad <= 0:
        return arr
    widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, widths, constant_values=fill)
