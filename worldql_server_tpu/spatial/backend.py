"""The swappable spatial-subscription engine interface.

This is the seam the whole rebuild pivots on (BASELINE.json north
star): the reference hard-wires a ``WorldMap → AreaMap → CubeArea``
HashMap pipeline into its handlers (subscriptions/world_map.rs,
area_map.rs); here every subscription mutation and proximity query goes
through ``SpatialBackend``, so the dict-based CPU engine and the
batched JAX/TPU engine are interchangeable and property-tested against
each other.

Peers are identified by ``uuid.UUID`` at this boundary; backends may
intern them to dense ints internally. Positions are accepted either as
raw ``Vector3`` (quantized by the backend at the configured cube size)
or as already-quantized ``(cx, cy, cz)`` int tuples — mirroring the
reference's ``ToCubeArea`` trait (cube_area.rs:61-78).
"""

from __future__ import annotations

import abc
import uuid as uuid_mod
from dataclasses import dataclass
from typing import Iterable, Sequence

from ..protocol.types import Replication, Vector3
from .quantize import cube_coords

Cube = tuple[int, int, int]
PosOrCube = "Vector3 | Cube"


def to_cube(pos: Vector3 | Cube, cube_size: int) -> Cube:
    """ToCubeArea: a Vector3 quantizes; a cube passes through
    (cube_area.rs:61-78)."""
    if isinstance(pos, Vector3):
        return cube_coords(pos.x, pos.y, pos.z, cube_size)
    return pos


@dataclass(slots=True)
class LocalQuery:
    """One LocalMessage proximity query in a tick batch."""

    world: str  # sanitized world name
    position: Vector3
    sender: uuid_mod.UUID
    replication: Replication = Replication.EXCEPT_SELF
    #: query-library kind (queries/kinds.py): 0 = plain radius row,
    #: anything else routes through the kind-dispatched expansion with
    #: ``params`` carrying the parsed f64 parameter lanes
    kind: int = 0
    params: tuple = ()


class SpatialBackend(abc.ABC):
    """Subscription index + proximity query engine for all worlds."""

    #: query-library expansion clamps (engine/config.py wires the
    #: ``query_stencil_max`` / ``query_ray_steps`` flags through;
    #: oracles and device expansion read the SAME values, so the clamp
    #: is part of the query semantics on both paths)
    query_stencil_max: int = 3
    query_ray_steps: int = 64

    def __init__(self, cube_size: int):
        self.cube_size = cube_size

    # region: mutations

    @abc.abstractmethod
    def add_subscription(
        self, world: str, peer: uuid_mod.UUID, pos: Vector3 | Cube
    ) -> bool:
        """Subscribe peer to the cube containing ``pos`` in ``world``.
        Creates the world lazily. Returns True if newly added
        (area_map.rs:72-85)."""

    @abc.abstractmethod
    def remove_subscription(
        self, world: str, peer: uuid_mod.UUID, pos: Vector3 | Cube
    ) -> bool:
        """Unsubscribe peer from one cube. Returns True if a
        subscription was removed (area_map.rs:88-119)."""

    @abc.abstractmethod
    def remove_peer(self, peer: uuid_mod.UUID) -> bool:
        """Remove a disconnected peer from every world/cube
        (world_map.rs:41-61)."""

    # endregion

    # region: queries

    @abc.abstractmethod
    def query_cube(self, world: str, pos: Vector3 | Cube) -> set[uuid_mod.UUID]:
        """Peers subscribed to the cube containing ``pos``; empty set if
        the world has never been subscribed to (area_map.rs:52-60)."""

    @abc.abstractmethod
    def query_world(self, world: str) -> set[uuid_mod.UUID]:
        """Peers subscribed to at least one cube of ``world``
        (area_map.rs:65-67)."""

    def is_subscribed(
        self, world: str, peer: uuid_mod.UUID, pos: Vector3 | Cube
    ) -> bool:
        return peer in self.query_cube(world, pos)

    def is_subscribed_any(self, world: str, peer: uuid_mod.UUID) -> bool:
        return peer in self.query_world(world)

    # endregion

    # region: batched hot path

    def match_local_batch(
        self, queries: Sequence[LocalQuery]
    ) -> list[list[uuid_mod.UUID]]:
        """Resolve a tick's worth of LocalMessage queries to fan-out
        lists, applying each query's replication filter
        (local_message.rs:60-86).

        Base implementation loops ``query_cube``; accelerated backends
        override with one fused device batch. Kind queries (``q.kind``
        != 0) resolve through the library's CPU-parity oracles
        (queries/oracle.py) to a ``KindResult`` row — this IS the
        reference path the device expansion is pinned against, and the
        degraded path ResilientBackend's CPU mirror answers with.
        """
        out: list = []
        for q in queries:  # wql: allow(per-query-python-loop) — the CPU reference path IS per-query
            if q.kind:
                from ..queries.oracle import match_kind

                out.append(match_kind(
                    self, q, q.params,
                    stencil_max=self.query_stencil_max,
                    ray_steps_max=self.query_ray_steps,
                ))
                continue
            peers = self.query_cube(q.world, q.position)
            out.append(_apply_replication(peers, q.sender, q.replication))
        return out

    def export_rows(self):
        """→ (worlds, peers, row_wid, row_cube, row_pid): every live
        subscription as index rows for snapshotting (spatial/
        snapshot.py). Each backend implements this against its own
        internals — a backend without it loses its shutdown checkpoint,
        so fail loudly rather than silently."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement export_rows — "
            "its index cannot be snapshotted"
        )

    def flush(self) -> None:
        """Make all prior mutations visible to queries. No-op for
        immediate-mode backends; device-mirror backends sync here."""

    # Two-phase batch API for the tick batcher: ``dispatch`` runs on the
    # owning thread (may read mutable host state), ``collect`` only
    # waits for results and may run on a worker thread. Immediate-mode
    # backends resolve everything in dispatch.
    def dispatch_local_batch(self, queries: Sequence[LocalQuery]):
        return self.match_local_batch(queries)

    def collect_local_batch(self, handle) -> list[list[uuid_mod.UUID]]:
        return handle

    # Columnar staged dispatch (engine/staging.py): backends that can
    # launch a batch straight from preallocated columnar arrays
    # (world_id i32, pos f64[·,3], sender_id i32, repl i8 — interned at
    # enqueue time by the ticker's staging buffers) advertise it here,
    # killing the per-query Python encode loop at flush time. The
    # object-list API above remains the default path (CPU backend,
    # staging off) byte for byte.
    def supports_staged_dispatch(self) -> bool:
        return False

    def interning_maps(self):
        """→ ``(world_name → id, peer_uuid → id)`` dicts the staging
        buffers intern through at enqueue time. Only meaningful when
        :meth:`supports_staged_dispatch` is True; the dicts are owned
        (and only mutated) by the event-loop thread."""
        raise NotImplementedError(
            f"{type(self).__name__} has no interning tables"
        )

    def staging_epoch(self) -> int:
        """Monotone counter that changes whenever previously interned
        ids stop being valid (e.g. a resilience rebuild swapped the
        inner backend). The ticker falls back to the object-list path
        for any staged window whose epoch went stale."""
        return 0

    def dispatch_staged_batch(
        self, world_ids, positions, sender_ids, repls,
        kinds=None, params=None, fallback=None,
    ):
        """Launch a batch from staged columnar arrays (already
        interned). ``kinds``/``params`` are the query-library lanes
        (i8 kind + f64 parameter rows); ``None`` — or an all-zero kind
        column — is the pure-radius fast path, byte-for-byte the
        pre-library pipeline. ``fallback`` is an opaque sequence of
        ``(message, LocalQuery)`` pairs a degraded wrapper may use to
        re-resolve the batch without the columns (robustness/
        resilient.py); array backends ignore it."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support staged dispatch"
        )

    # endregion


def _apply_replication(
    peers: Iterable[uuid_mod.UUID],
    sender: uuid_mod.UUID,
    replication: Replication,
) -> list[uuid_mod.UUID]:
    if replication == Replication.EXCEPT_SELF:
        return [p for p in peers if p != sender]
    if replication == Replication.ONLY_SELF:
        return [p for p in peers if p == sender]
    return list(peers)
