"""Boot-time capacity-tier precompilation for the device fan-out engine.

A first-occurrence capacity tier pays its jit trace MID-SERVING — tens
of milliseconds to seconds inside a 5 ms tick budget (the BENCH_r05
207 s outlier is this failure mode at its worst; utils/retrace.py is
the tripwire). The engine's shapes are all power-of-two tiers, so the
set a configuration can reach is small and enumerable: this module
walks it BEFORE serving starts — every query-cap tier up to
``max_batch``, the CSR slot-capacity ladder each of those can request
(zone-A floor upward, below the dense ceiling), and the pack-bucket
tiers of the on-device result compaction — dispatching each shape once
against the backend's real device segments (shapes and dtypes are what
jit keys on; the dummy query values match nothing and the results are
discarded).

Scope and honesty: precompilation covers the index PRESENT at boot
(after a snapshot restore, that is the serving index; an empty-index
boot has no segments to trace against and skips with a log line — the
first subscription's delta tier still pays its first trace). The
sustained bench run is the proof: with precompilation on, the PR 7
retrace GUARD must report ``device.retraces == 0`` across the pass.

Cost is bounded: ``max_compiles`` caps the walk (largest shapes first —
peak traffic is where a mid-serving trace hurts), and every dispatch
is synchronized so boot completes with the caches warm, not merely
enqueued.
"""

from __future__ import annotations

import logging
import time

import numpy as np

from ..utils.retrace import GUARD
from .hashing import next_pow2

logger = logging.getLogger(__name__)

#: zone-A identity-row width (tpu_backend.CSR_ROW — imported lazily to
#: keep this module importable without jax)
_CSR_ROW = 8


def query_cap_ladder(backend, max_batch: int, min_batch: int | None):
    """Descending, deduped query-capacity tiers the ticker can reach:
    ``next_pow2(m)`` for every batch size up to ``max_batch`` collapses
    to a halving ladder; ``min_batch`` floors it (tiny tiers trace in
    microseconds of traffic and are rarely worth boot time)."""
    if min_batch is None:
        min_batch = max(64, max_batch // 8)
    ms, m = [], max(1, int(max_batch))
    while m >= min_batch:
        ms.append(m)
        m //= 2
    if not ms:
        ms.append(max(1, int(max_batch)))
    seen, out = set(), []
    for m in ms:
        cap = backend._query_cap(m)
        if cap not in seen:
            seen.add(cap)
            out.append((m, cap))
    return out


def _precompile_kind_tiers(backend, max_batch: int,
                           *, max_calls: int = 96) -> dict:
    """Query-library leg of the boot walk: warm every REGISTERED
    kind's stencil kernel (queries/geometry.py, queries/knn.py) over
    the kind-row tier ladder × the reachable stencil radii. The row
    wrappers pad to pow2 tiers (geometry.KIND_ROW_FLOOR), so this
    ladder is exactly the shape set serving can hit — with it walked,
    a mixed-kind tick after boot keeps ``device.retraces == 0``. The
    kernels are tiny (elementwise masks + one row sort), so the leg
    gets its own small budget instead of competing with the dispatch
    walk."""
    try:
        from ..queries.geometry import (
            KIND_ROW_FLOOR, precompile_kind_kernels,
        )
        from ..queries.kinds import registered_kinds
    except Exception:  # pragma: no cover - library unavailable/broken
        logger.exception("kind-kernel precompilation unavailable")
        return {"kind_dispatches": 0}
    if not registered_kinds():
        return {"kind_dispatches": 0}
    calls = skipped = 0
    stencil_max = int(getattr(backend, "query_stencil_max", 3))
    tier = next_pow2(max(1, int(max_batch)), floor=KIND_ROW_FLOOR)
    while tier >= KIND_ROW_FLOOR:
        # largest shapes first, same priority logic as the main walk
        for radius in range(1, stencil_max + 1):
            if calls >= max_calls:
                skipped += 1
                continue
            calls += precompile_kind_kernels(
                tier, radius, backend.cube_size
            )
        tier //= 2
    return {"kind_dispatches": calls, "kind_skipped_by_budget": skipped}


def precompile_tiers(
    backend,
    *,
    max_batch: int,
    min_batch: int | None = None,
    t_tiers: int = 4,
    include_pack: bool = True,
    max_compiles: int = 64,
    delivery_cap: int | None = None,
    kind_tiers: bool = True,
) -> dict:
    """Trace every reachable hot-path kernel shape before serving.

    ``t_tiers`` bounds the CSR slot-capacity doublings walked above
    each query tier's zone-A floor (the adaptive ``_delivery_cap`` can
    climb that ladder at runtime; covering a few doublings of headroom
    keeps an overflow retry off the compile path too). Returns a stats
    dict — ``new_variants`` is the retrace-GUARD delta this warmup
    compiled, the same accounting serving retraces are measured by.
    """
    t0 = time.perf_counter()
    if min_batch is None and getattr(backend, "_delta_ticks", False):
        # delta ticks dispatch the DIRTY fraction of each batch at its
        # own (small) query tier — with reuse doing its job those are
        # exactly the tiers serving lives on, so the ladder walks all
        # the way down instead of stopping at the max_batch//8 floor
        min_batch = 8
    flush = getattr(backend, "flush", None)
    if flush is not None:
        flush()
    segs, ks, kinds = backend._segments()
    if not segs:
        logger.info(
            "tier precompilation skipped: empty index (no device "
            "segments to trace against)"
        )
        # the kind stencil kernels trace against parameter shapes only
        # — no index needed, so an empty-index boot still warms them
        kind_stats = (
            _precompile_kind_tiers(backend, max_batch) if kind_tiers
            else {"kind_dispatches": 0}
        )
        return {"skipped": "empty-index", "new_variants": 0,
                "dispatches": 0, "pack_calls": 0, "wall_ms": 0.0,
                **kind_stats}

    before = GUARD.counts()
    nseg = len(segs)
    base_cap = (
        delivery_cap if delivery_cap is not None
        else getattr(backend, "_delivery_cap", 4096)
    )
    min_bucket = getattr(backend, "compact_min_bucket", 1 << 10)
    dispatches = pack_calls = skipped = 0
    budget = max(1, int(max_compiles))

    #: dense [M, K] tables above this many lanes are a memory/compile
    #: hazard to trace speculatively — serving only reaches them
    #: through the rare overflow re-resolve, which pays its own trace
    dense_lane_budget = 1 << 24

    for m, qcap in query_cap_ladder(backend, max_batch, min_batch):
        if dispatches + pack_calls >= budget:
            skipped += 1
            continue
        qtuple = backend._prepare_queries(
            np.full(m, -1, np.int32),
            np.zeros((m, 3), np.float64),
            np.full(m, -1, np.int32),
            np.zeros(m, np.int8),
        )
        ceiling = next_pow2(m * sum(ks))
        # serving's tier choice (tpu_backend._dispatch_encoded): the
        # CSR path at max(adaptive delivery cap, zone-A floor), dense
        # once that reaches the fan-out ceiling — and dense is ALSO the
        # overflow re-resolve at any tier, so trace it whenever its
        # table is sanely sized
        zone_floor = next_pow2(_CSR_ROW * qcap * nseg + 64)
        current = next_pow2(max(base_cap, zone_floor))
        if qcap * sum(ks) <= dense_lane_budget:
            tgt = backend._dispatch(qtuple, segs, ks, kinds)
            getattr(tgt, "block_until_ready", lambda: None)()
            dispatches += 1
        # CSR slot-capacity ladder: from the zone-A floor (the tier a
        # decayed delivery cap lands on) through the current cap plus
        # headroom doublings (the tiers an overflow retry climbs to)
        top = max(current, zone_floor) << max(0, int(t_tiers) - 1)
        seen_caps: set[int] = set()
        t_cap = zone_floor
        while t_cap < ceiling and t_cap <= top:
            eff = backend._csr_effective_cap(t_cap, qtuple, segs)
            t_cap *= 2
            if eff in seen_caps:
                continue
            seen_caps.add(eff)
            if dispatches + pack_calls >= budget:
                skipped += 1
                break
            result = backend._dispatch_csr(qtuple, segs, ks, kinds, eff)
            # synchronize: boot must end with the cache WARM, not with
            # a compile still in flight behind an async dispatch
            int(np.asarray(result[2]))
            dispatches += 1
            if not include_pack:
                continue
            # pack-bucket ladder for this capacity tier: feed the tier's
            # own device result through the compaction at each bucket
            # total the runtime can request (the call is the serving
            # path — _compact_fetch no-ops below its min-cap gate)
            bucket = min_bucket
            while bucket * 2 <= eff:
                if dispatches + pack_calls >= budget:
                    skipped += 1
                    break
                backend._compact_fetch(result[0], result[1], bucket, eff)
                pack_calls += 1
                bucket *= 2

    kind_stats = (
        _precompile_kind_tiers(backend, max_batch) if kind_tiers
        else {"kind_dispatches": 0}
    )
    delta = GUARD.delta(before)
    stats = {
        "dispatches": dispatches,
        "pack_calls": pack_calls,
        "skipped_by_budget": skipped,
        "new_variants": sum(delta.values()),
        "families": delta,
        "wall_ms": round((time.perf_counter() - t0) * 1e3, 1),
        **kind_stats,
    }
    logger.info(
        "tier precompilation: %d dispatch + %d pack shapes walked, "
        "%d new kernel variants compiled in %.0f ms%s",
        dispatches, pack_calls, stats["new_variants"], stats["wall_ms"],
        f" ({skipped} skipped by budget)" if skipped else "",
    )
    return stats
