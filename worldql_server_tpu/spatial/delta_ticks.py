"""Temporal-coherence state for delta ticks (ROADMAP item 2).

Tick over tick the query set is near-identical and most entities move
less than one cube — exactly the regime of repeated range queries over
massive moving objects (arXiv:1411.3212). Yet every tick the engine
re-resolved EVERY query from scratch. This module holds the state that
lets a tick skip the world that did not change:

* **per-cube dirty tracking** — every index mutation marks the touched
  cube's spatial key with a monotonically increasing mutation sequence
  number, fed from the same churn stream the LSM delta path already
  sees (the host is the authority; marking costs one dict store per
  touched cube);
* **result reuse cache** — a query whose 128-bit content signature
  (world id, position bits, sender, replication — two independent
  64-bit mixes, the same collision budget as the index's dual key
  families) matched a cached entry AND whose cube has not been dirtied
  since the entry was computed replays the cached fan-out instead of
  re-entering the device batch. Only dirty queries ship to the device,
  at a (smaller) power-of-two capacity tier the boot precompile ladder
  already covers.

Validity invariant: an entry computed at mutation-sequence ``seq``
reflects every mutation with sequence <= ``seq`` (the dispatch flushes
them to the device before computing). A later mutation of the entry's
cube records a larger sequence in ``dirty``, so the check
``dirty.get(key, -1) <= entry.seq`` is exact — no grace window, no
staleness bound to document. Wholesale events that rewrite keys or
membership (reseed, base rebuild, snapshot restore, resilience
rebuild) call :meth:`invalidate_all`, which raises ``floor`` past any
in-flight entry's sequence — entries inserted by a worker-thread
collect that raced the invalidation fail the ``seq >= floor`` check
and can never be replayed.

Threading: mutations and dispatch partitioning run on the event-loop
thread; cache inserts run on the ticker's collect worker thread.
Every shared structure is a plain dict mutated one key at a time with
immutable tuple values, so a racing read sees either the old or the
new entry — both valid under the sequence check.
"""

from __future__ import annotations

import numpy as np

from .hashing import MIX_GOLDEN, MIX_M1, MIX_M2

#: cache entries above which the cache resets wholesale (a workload of
#: ever-fresh positions — pure miss traffic — must not grow host memory
#: without bound; steady serving sits orders of magnitude below this)
MAX_CACHE_ENTRIES = 1 << 20
#: dirty-map entries above which tracking resets wholesale (same
#: rationale; a reset only costs one cold tick of full recompute)
MAX_DIRTY_ENTRIES = 1 << 21

_M1 = np.uint64(MIX_M1)
_M2 = np.uint64(MIX_M2)
_GOLDEN = np.uint64(MIX_GOLDEN)
#: signature seeds — disjoint from the index's key families (hashing.py
#: uses the raw seed and seed + KEY2_OFFSET; these fold a distinct
#: constant first, so a signature can never alias a spatial key stream)
_SIG_SEED1 = np.uint64(0x9E3779B97F4A7C15)
_SIG_SEED2 = np.uint64(0xC2B2AE3D27D4EB4F)


def _mix(x: np.ndarray) -> np.ndarray:
    x = (x ^ (x >> np.uint64(30))) * _M1
    x = (x ^ (x >> np.uint64(27))) * _M2
    return x ^ (x >> np.uint64(31))


def _fold(seed: np.uint64, world_ids, pos_bits, sender_ids, repls):
    h = _mix(seed + _GOLDEN)
    h = _mix(h ^ world_ids)
    h = _mix(h ^ pos_bits[:, 0])
    h = _mix(h ^ pos_bits[:, 1])
    h = _mix(h ^ pos_bits[:, 2])
    h = _mix(h ^ sender_ids)
    return _mix(h ^ repls)


def row_signatures(
    world_ids: np.ndarray,
    positions: np.ndarray,
    sender_ids: np.ndarray,
    repls: np.ndarray,
) -> tuple[np.ndarray, np.ndarray]:
    """[M] staged query columns → two independent [M] u64 content
    signatures. Everything that can change a query's fan-out folds in:
    interned world id, the raw f64 position BITS (so -0.0 vs 0.0 or a
    NaN payload can never alias), interned sender and replication.
    Vectorized — one fused numpy pass, no per-row Python."""
    with np.errstate(over="ignore"):
        wid = world_ids.astype(np.int64).view(np.uint64)
        pos_bits = np.ascontiguousarray(
            positions, dtype=np.float64
        ).view(np.uint64)
        sid = sender_ids.astype(np.int64).view(np.uint64)
        rep = repls.astype(np.int64).view(np.uint64)
        return (
            _fold(_SIG_SEED1, wid, pos_bits, sid, rep),
            _fold(_SIG_SEED2, wid, pos_bits, sid, rep),
        )


class TemporalCoherence:
    """Dirty-cube sequence map + result-reuse cache for one backend."""

    def __init__(self, max_entries: int = MAX_CACHE_ENTRIES):
        #: mutation sequence — bumped once per mutation batch
        self.seq = 0
        #: entries with ``seq < floor`` are invalid (wholesale events)
        self.floor = 0
        #: cube spatial key → sequence of its latest mutation
        self.dirty: dict[int, int] = {}
        #: signature h1 → (h2, cube_key, seq, targets_tuple)
        self.cache: dict[int, tuple] = {}
        self.max_entries = max_entries
        #: cubes marked since the last dispatch (tick.delta churn tag)
        self.window_marks = 0
        self.cache_resets = 0

    # -- churn stream (event-loop thread) --

    def note_key(self, key: int) -> None:
        """Mark one cube dirty (single-subscription mutation path)."""
        self.seq += 1
        self.dirty[key] = self.seq
        self.window_marks += 1
        if len(self.dirty) > MAX_DIRTY_ENTRIES:
            self.invalidate_all()

    def note_keys(self, keys) -> None:
        """Mark a mutation batch's cubes dirty: one sequence bump, one
        C-level dict fill (``keys`` is an int64 array or int list)."""
        if len(keys) == 0:
            return
        self.seq += 1
        s = self.seq
        if isinstance(keys, np.ndarray):
            keys = keys.tolist()
        self.dirty.update(zip(keys, [s] * len(keys)))
        self.window_marks += len(keys)
        if len(self.dirty) > MAX_DIRTY_ENTRIES:
            self.invalidate_all()

    def invalidate_all(self) -> None:
        """Wholesale invalidation (reseed/rebuild/restore): every
        existing entry — including ones a racing worker-thread collect
        has not inserted yet — becomes unreplayable."""
        self.seq += 1
        self.floor = self.seq
        self.dirty.clear()
        self.cache.clear()
        self.cache_resets += 1

    # -- dispatch partition (event-loop thread) --

    def take_window_marks(self) -> int:
        marks = self.window_marks
        self.window_marks = 0
        return marks

    def partition(self, h1_list, h2_list):
        """→ ``(reused, dirty_rows)``: per-row replayed target lists
        (None where the row must recompute) and the row indices of the
        compute batch. One C-speed bulk dict probe plus a per-row
        validity check against the dirty map."""
        cache_get = self.cache.get
        dirty_get = self.dirty.get
        floor = self.floor
        reused: list = [None] * len(h1_list)
        dirty_rows: list[int] = []
        for i, (h1, h2) in enumerate(zip(h1_list, h2_list)):
            e = cache_get(h1)
            if (
                e is not None
                and e[0] == h2
                and e[2] >= floor
                and dirty_get(e[1], -1) <= e[2]
            ):
                reused[i] = list(e[3])
            else:
                dirty_rows.append(i)
        return reused, dirty_rows

    # -- collect merge (worker thread) --

    def store(self, h1: int, h2: int, key: int, seq: int, targets) -> None:
        if len(self.cache) >= self.max_entries:
            # ever-fresh signatures (pure miss traffic): reset rather
            # than grow without bound — one cold tick, never wrong
            self.cache.clear()
            self.cache_resets += 1
        self.cache[h1] = (h2, key, seq, tuple(targets))

    def stats(self) -> dict:
        return {
            "entries": len(self.cache),
            "dirty_cubes": len(self.dirty),
            "seq": self.seq,
            "cache_resets": self.cache_resets,
        }
