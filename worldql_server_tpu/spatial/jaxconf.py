"""JAX configuration shared by every accelerated module.

Cube labels are int64 (the reference's CubeArea is i64×3,
subscriptions/cube_area.rs:8-13) and the sort keys derived from them are
64-bit hashes, so the device path needs x64 enabled. TPU executes i64
compares/gathers as emulated pairs of i32 ops — cheap for this workload,
which is bandwidth-bound gathers, not arithmetic. No f64 ever reaches
the device: quantization runs host-side in numpy f64 (spatial/quantize).

Import this module before any ``import jax`` in accelerated code.
"""

from __future__ import annotations

import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: the large-tier device kernels (1M-row
# segment sorts, probe-table builds, match kernels) cost 30-45s EACH to
# compile on TPU, which dominated cold-start index builds (a 1M-sub
# bulk load spent ~90s compiling vs ~0.5s executing). The cache cuts
# every process after the first to sub-second loads of the serialized
# executables (measured 30.5s -> 3.6s on v5e through the axon tunnel).
# Default: next to the package (a checkout's benches/tests/servers
# share it) when that directory is writable — site-packages installs
# usually are not, so fall back to the user cache dir rather than
# silently losing the cache (and spamming write warnings) in exactly
# the deployed case. Override with WQL_JAX_CACHE_DIR, disable with
# WQL_JAX_CACHE_DIR="".


def _default_cache_dir() -> str:
    repo_adjacent = os.path.join(
        os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
        ".jax_cache",
    )
    # probe the directory itself when it exists (it may belong to
    # another uid), its parent otherwise
    probe = repo_adjacent if os.path.isdir(repo_adjacent) \
        else os.path.dirname(repo_adjacent)
    if os.access(probe, os.W_OK):
        return repo_adjacent
    return os.path.join(
        os.environ.get(
            "XDG_CACHE_HOME", os.path.expanduser("~/.cache")
        ),
        "worldql_server_tpu", "jax_cache",
    )


_cache_dir = os.environ.get("WQL_JAX_CACHE_DIR", _default_cache_dir())
if _cache_dir:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

# Honor a virtual-CPU-mesh request (tests, multi-chip dry runs on hosts
# without a TPU slice). The TPU plugin in this image registers itself
# at interpreter startup via a .pth hook, so JAX_PLATFORMS from the
# environment arrives too late to stop it — inspect the env here and
# override via config before the first backend initialization.
if (
    "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", "")
    or os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
):
    jax.config.update("jax_platform_name", "cpu")
