"""JAX configuration shared by every accelerated module.

Cube labels are int64 (the reference's CubeArea is i64×3,
subscriptions/cube_area.rs:8-13) and the sort keys derived from them are
64-bit hashes, so the device path needs x64 enabled. TPU executes i64
compares/gathers as emulated pairs of i32 ops — cheap for this workload,
which is bandwidth-bound gathers, not arithmetic. No f64 ever reaches
the device: quantization runs host-side in numpy f64 (spatial/quantize).

Import this module before any ``import jax`` in accelerated code.
"""

from __future__ import annotations

import jax

jax.config.update("jax_enable_x64", True)
