from .quantize import (
    clamp_region_coord,
    clamp_region_coord_batch,
    clamp_table_size,
    coord_clamp,
    coord_clamp_batch,
    cube_coords,
    cube_coords_batch,
    region_coords,
    region_coords_batch,
    table_bounds,
)

__all__ = [
    "coord_clamp",
    "coord_clamp_batch",
    "cube_coords",
    "cube_coords_batch",
    "clamp_region_coord",
    "clamp_region_coord_batch",
    "clamp_table_size",
    "region_coords",
    "region_coords_batch",
    "table_bounds",
]
