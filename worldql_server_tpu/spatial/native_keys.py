"""Native query-key preparation (ctypes binding for native/spatial.cpp).

One C++ pass fuses cube quantization with both spatial hashes — the
per-tick host-side cost of the fan-out engine (~4 ms per 16K-query
batch in numpy, dominated by intermediate arrays the fused loop never
materializes). Falls back to the numpy twins transparently; the
property suite (tests/test_native_keys.py) pins bit-exact agreement
including NaN/±inf/exact-multiple/saturation edge cases.

Two entry points:

* :func:`query_keys` — quantize + both hashes for an [N] batch
  (``wql_query_keys``).
* :func:`encode_queries` — the full dispatch-ready encode
  (``wql_encode_queries``): quantize + hash + capacity-tier padding of
  all four query columns straight from the ticker's staging arrays, one
  GIL-releasing C call (ctypes drops the GIL for the duration), zero
  numpy intermediates. Padding lanes match spatial/hashing.py
  (PAD_KEY / QUERY_PAD_KEY2 / sender -1 / repl 0) — pinned by the
  parity suite. A stale ``.so`` built before this symbol existed keeps
  serving ``query_keys`` and the encode composes the two-step path.
"""

from __future__ import annotations

import ctypes
import logging

import numpy as np

from ..protocol.native_codec import resolve_lib_path
from .hashing import (
    KEY2_OFFSET, PAD_KEY, QUERY_PAD_KEY2, pad_to, spatial_keys,
    spatial_keys2,
)
from .quantize import cube_coords_batch

logger = logging.getLogger(__name__)

_U64_MASK = (1 << 64) - 1


class _NativeKeys:
    def __init__(self, lib: ctypes.CDLL):
        self._fn = lib.wql_query_keys
        self._fn.restype = None
        self._fn.argtypes = [
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        # Reference-calibration probe (ROADMAP 5a): newer symbol,
        # probed separately like the encode below.
        self._areamap = getattr(lib, "wql_areamap_probe", None)
        if self._areamap is not None:
            self._areamap.restype = ctypes.c_int64
            self._areamap.argtypes = [
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_double),
            ]
        # The fused batch encode is newer than wql_query_keys — probe
        # it separately so a stale library degrades to the two-step
        # path instead of losing the native keys entirely.
        self._encode = getattr(lib, "wql_encode_queries", None)
        if self._encode is not None:
            self._encode.restype = None
            self._encode.argtypes = [
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int8),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_uint64, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int8),
            ]

    def __call__(self, world_ids, positions, cube_size: int, seed: int):
        n = len(world_ids)
        pos = np.ascontiguousarray(positions, dtype=np.float64)
        wid = np.ascontiguousarray(world_ids, dtype=np.int32)
        if pos.shape != (n, 3):
            # the numpy twin raises a broadcast error here; the C call
            # would read past the buffer
            raise ValueError(
                f"positions shape {pos.shape} != ({n}, 3)"
            )
        k1 = np.empty(n, np.int64)
        k2 = np.empty(n, np.int64)
        self._fn(
            pos.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            wid.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n, cube_size,
            ctypes.c_uint64(seed & _U64_MASK),
            ctypes.c_uint64((seed + KEY2_OFFSET) & _U64_MASK),
            k1.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            k2.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return k1, k2

    def encode(self, world_ids, positions, sender_ids, repls, cap: int,
               cube_size: int, seed: int):
        if self._encode is None:
            return None
        n = len(world_ids)
        pos = np.ascontiguousarray(positions, dtype=np.float64)
        wid = np.ascontiguousarray(world_ids, dtype=np.int32)
        sid = np.ascontiguousarray(sender_ids, dtype=np.int32)
        rep = np.ascontiguousarray(repls, dtype=np.int8)
        if pos.shape != (n, 3):
            raise ValueError(f"positions shape {pos.shape} != ({n}, 3)")
        if len(sid) != n or len(rep) != n or cap < n:
            raise ValueError("encode_queries column lengths disagree")
        k1 = np.empty(cap, np.int64)
        k2 = np.empty(cap, np.int64)
        sid_out = np.empty(cap, np.int32)
        rep_out = np.empty(cap, np.int8)
        self._encode(
            pos.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            wid.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            sid.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            rep.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
            n, cap, cube_size,
            ctypes.c_uint64(seed & _U64_MASK),
            ctypes.c_uint64((seed + KEY2_OFFSET) & _U64_MASK),
            k1.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            k2.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            sid_out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            rep_out.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        )
        return k1, k2, sid_out, rep_out


def load() -> _NativeKeys | None:
    """Load the native key kernel, or None (numpy fallback)."""
    lib_path = resolve_lib_path()
    if lib_path is None or not lib_path.exists():
        return None
    try:
        lib = ctypes.CDLL(str(lib_path))
        if lib.wql_spatial_abi() != 1:
            logger.warning("native spatial ABI mismatch — using numpy")
            return None
        return _NativeKeys(lib)
    except (OSError, AttributeError) as exc:
        # a stale .so without the symbol must not kill the server
        logger.warning("native key kernel unavailable: %s", exc)
        return None


_native = load()


def query_keys(world_ids, positions, cube_size: int, seed: int):
    """[N] i32 world ids + [N, 3] f64 positions → (keys1, keys2), via
    the native fused kernel when built, numpy twins otherwise."""
    if _native is not None:
        return _native(world_ids, positions, cube_size, seed)
    cubes = cube_coords_batch(positions, cube_size)
    return (
        spatial_keys(world_ids, cubes, seed),
        spatial_keys2(world_ids, cubes, seed),
    )


def areamap_probe(n_subs: int, n_queries: int, cube_size: int = 16,
                  seed: int = 11) -> dict | None:
    """Reference-class CPU calibration (``wql_areamap_probe``): build
    a reference-shaped cube→peers hash map of ``n_subs`` rows and
    resolve ``n_queries`` lookups against it, single native thread —
    the ``vs_reference`` row in the bench JSON. None when the native
    library predates the symbol (the bench row degrades to absent,
    never wrong)."""
    if _native is None or getattr(_native, "_areamap", None) is None:
        return None
    out = np.zeros(3, np.float64)
    rc = _native._areamap(
        int(n_subs), int(n_queries), int(cube_size),
        ctypes.c_uint64(seed & _U64_MASK),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    if rc != 0:
        return None
    return {
        "subs": int(n_subs),
        "queries": int(n_queries),
        "build_ms": round(float(out[0]), 3),
        "lookup_ns_per_query": round(float(out[1]), 1),
        "matched_rows": int(out[2]),
    }


def numpy_query_keys(world_ids, positions, cube_size: int, seed: int):
    """The pure-numpy path, exposed for the parity suite."""
    cubes = cube_coords_batch(positions, cube_size)
    return (
        spatial_keys(world_ids, cubes, seed),
        spatial_keys2(world_ids, cubes, seed),
    )


def encode_queries(world_ids, positions, sender_ids, repls, cap: int,
                   cube_size: int, seed: int):
    """Full dispatch-ready query encode: → ``(keys1[cap], keys2[cap],
    senders[cap] i32, repls[cap] i8)``, padded to the ``cap`` capacity
    tier. One fused native pass when the kernel is built; the composed
    query_keys + pad_to path otherwise (bit-identical, pinned by
    tests/test_native_keys.py)."""
    if _native is not None:
        out = _native.encode(
            world_ids, positions, sender_ids, repls, cap, cube_size, seed
        )
        if out is not None:
            return out
    return numpy_encode_queries(
        world_ids, positions, sender_ids, repls, cap, cube_size, seed
    )


def numpy_encode_queries(world_ids, positions, sender_ids, repls,
                         cap: int, cube_size: int, seed: int):
    """The composed two-step encode, exposed for the parity suite (and
    the fallback when the fused symbol is absent). Uses query_keys —
    which may itself be native — so a stale library still accelerates
    the hash leg."""
    keys, keys2 = query_keys(world_ids, positions, cube_size, seed)
    return (
        pad_to(keys, cap, PAD_KEY),
        pad_to(keys2, cap, QUERY_PAD_KEY2),
        pad_to(
            np.ascontiguousarray(sender_ids, dtype=np.int32), cap,
            np.int32(-1),
        ),
        pad_to(
            np.ascontiguousarray(repls, dtype=np.int8), cap, np.int8(0)
        ),
    )
