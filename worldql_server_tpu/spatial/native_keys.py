"""Native query-key preparation (ctypes binding for native/spatial.cpp).

One C++ pass fuses cube quantization with both spatial hashes — the
per-tick host-side cost of the fan-out engine (~4 ms per 16K-query
batch in numpy, dominated by intermediate arrays the fused loop never
materializes). Falls back to the numpy twins transparently; the
property suite (tests/test_native_keys.py) pins bit-exact agreement
including NaN/±inf/exact-multiple/saturation edge cases.
"""

from __future__ import annotations

import ctypes
import logging

import numpy as np

from ..protocol.native_codec import resolve_lib_path
from .hashing import KEY2_OFFSET, spatial_keys, spatial_keys2
from .quantize import cube_coords_batch

logger = logging.getLogger(__name__)

_U64_MASK = (1 << 64) - 1


class _NativeKeys:
    def __init__(self, lib: ctypes.CDLL):
        self._fn = lib.wql_query_keys
        self._fn.restype = None
        self._fn.argtypes = [
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]

    def __call__(self, world_ids, positions, cube_size: int, seed: int):
        n = len(world_ids)
        pos = np.ascontiguousarray(positions, dtype=np.float64)
        wid = np.ascontiguousarray(world_ids, dtype=np.int32)
        if pos.shape != (n, 3):
            # the numpy twin raises a broadcast error here; the C call
            # would read past the buffer
            raise ValueError(
                f"positions shape {pos.shape} != ({n}, 3)"
            )
        k1 = np.empty(n, np.int64)
        k2 = np.empty(n, np.int64)
        self._fn(
            pos.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            wid.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n, cube_size,
            ctypes.c_uint64(seed & _U64_MASK),
            ctypes.c_uint64((seed + KEY2_OFFSET) & _U64_MASK),
            k1.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            k2.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return k1, k2


def load() -> _NativeKeys | None:
    """Load the native key kernel, or None (numpy fallback)."""
    lib_path = resolve_lib_path()
    if lib_path is None or not lib_path.exists():
        return None
    try:
        lib = ctypes.CDLL(str(lib_path))
        if lib.wql_spatial_abi() != 1:
            logger.warning("native spatial ABI mismatch — using numpy")
            return None
        return _NativeKeys(lib)
    except (OSError, AttributeError) as exc:
        # a stale .so without the symbol must not kill the server
        logger.warning("native key kernel unavailable: %s", exc)
        return None


_native = load()


def query_keys(world_ids, positions, cube_size: int, seed: int):
    """[N] i32 world ids + [N, 3] f64 positions → (keys1, keys2), via
    the native fused kernel when built, numpy twins otherwise."""
    if _native is not None:
        return _native(world_ids, positions, cube_size, seed)
    cubes = cube_coords_batch(positions, cube_size)
    return (
        spatial_keys(world_ids, cubes, seed),
        spatial_keys2(world_ids, cubes, seed),
    )


def numpy_query_keys(world_ids, positions, cube_size: int, seed: int):
    """The pure-numpy path, exposed for the parity suite."""
    cubes = cube_coords_batch(positions, cube_size)
    return (
        spatial_keys(world_ids, cubes, seed),
        spatial_keys2(world_ids, cubes, seed),
    )
