"""Dict-based reference implementation of :class:`SpatialBackend`.

Observable semantics match the reference's WorldMap/AreaMap exactly
(subscriptions/world_map.rs, area_map.rs) — lazily-created worlds,
cube-keyed peer sets, and a world-level "subscribed to any cube" view.
One deliberate improvement: world-level membership is tracked with
per-peer cube refcounts, so ``remove_subscription`` and ``remove_peer``
are O(1)/O(own cubes) instead of the reference's O(all cubes) scans
(area_map.rs:113, area_map.rs:124-135) — same observable behavior.
"""

from __future__ import annotations

import uuid as uuid_mod
from collections import defaultdict

from ..protocol.types import Vector3
from .backend import Cube, SpatialBackend, to_cube


class _World:
    __slots__ = ("cubes", "peer_cube_count", "peer_cubes")

    def __init__(self) -> None:
        self.cubes: dict[Cube, set[uuid_mod.UUID]] = {}
        # peer -> number of cubes it is subscribed to (world-level view)
        self.peer_cube_count: dict[uuid_mod.UUID, int] = defaultdict(int)
        # peer -> set of cubes, for O(own cubes) disconnect cleanup
        self.peer_cubes: dict[uuid_mod.UUID, set[Cube]] = defaultdict(set)


class CpuSpatialBackend(SpatialBackend):
    def __init__(self, cube_size: int):
        super().__init__(cube_size)
        self._worlds: dict[str, _World] = {}

    # region: mutations

    def add_subscription(
        self, world: str, peer: uuid_mod.UUID, pos: Vector3 | Cube
    ) -> bool:
        cube = to_cube(pos, self.cube_size)
        w = self._worlds.get(world)
        if w is None:
            w = self._worlds[world] = _World()

        peers = w.cubes.setdefault(cube, set())
        if peer in peers:
            return False
        peers.add(peer)
        w.peer_cube_count[peer] += 1
        w.peer_cubes[peer].add(cube)
        return True

    def remove_subscription(
        self, world: str, peer: uuid_mod.UUID, pos: Vector3 | Cube
    ) -> bool:
        cube = to_cube(pos, self.cube_size)
        w = self._worlds.get(world)
        if w is None or cube not in w.cubes:
            return False

        peers = w.cubes[cube]
        if peer not in peers:
            return False
        peers.remove(peer)
        if not peers:
            del w.cubes[cube]  # empty-set GC (area_map.rs:108-110)

        w.peer_cubes[peer].discard(cube)
        w.peer_cube_count[peer] -= 1
        if w.peer_cube_count[peer] <= 0:
            del w.peer_cube_count[peer]
            del w.peer_cubes[peer]
        return True

    def bulk_add_subscriptions(self, world, peers, cubes) -> int:
        """Bulk-load peers[i] → cube rows [N, 3] (already quantized).
        Loader for benchmarks and snapshot restore."""
        added = 0
        for peer, cube in zip(peers, cubes):
            if self.add_subscription(
                world, peer, (int(cube[0]), int(cube[1]), int(cube[2]))
            ):
                added += 1
        return added

    def remove_peer(self, peer: uuid_mod.UUID) -> bool:
        removed = False
        for w in self._worlds.values():
            cubes = w.peer_cubes.pop(peer, None)
            if not cubes:
                w.peer_cube_count.pop(peer, None)
                continue
            removed = True
            w.peer_cube_count.pop(peer, None)
            for cube in cubes:
                peers = w.cubes.get(cube)
                if peers is not None:
                    peers.discard(peer)
                    if not peers:
                        del w.cubes[cube]
        return removed

    # endregion

    # region: queries

    def query_cube(self, world: str, pos: Vector3 | Cube) -> set[uuid_mod.UUID]:
        w = self._worlds.get(world)
        if w is None:
            return set()
        return set(w.cubes.get(to_cube(pos, self.cube_size), ()))

    def query_world(self, world: str) -> set[uuid_mod.UUID]:
        w = self._worlds.get(world)
        if w is None:
            return set()
        return set(w.peer_cube_count.keys())

    # endregion

    # region: query-library conveniences (tests, scenarios)

    def query_kind(self, query) -> "object":
        """Resolve one kind :class:`~worldql_server_tpu.spatial.backend.
        LocalQuery` through the CPU oracles — the named single-query
        face of the library (``match_local_batch`` is the batch
        face)."""
        from ..queries.oracle import match_kind

        return match_kind(
            self, query, query.params,
            stencil_max=self.query_stencil_max,
            ray_steps_max=self.query_ray_steps,
        )

    # endregion

    # region: introspection (tests, metrics)

    def world_names(self) -> list[str]:
        return list(self._worlds.keys())

    def export_rows(self):
        """Snapshot export (spatial/snapshot.py): live rows from the
        dict index."""
        import numpy as np

        worlds, rows = [], []
        peers, peer_ids = [], {}
        for world, w in self._worlds.items():
            wid_i = len(worlds)
            worlds.append(world)
            for cube_t, cube_peers in w.cubes.items():
                for peer in cube_peers:
                    pid_i = peer_ids.get(peer)
                    if pid_i is None:
                        pid_i = peer_ids[peer] = len(peers)
                        peers.append(peer)
                    rows.append((wid_i, *cube_t, pid_i))
        arr = np.asarray(rows, np.int64).reshape(-1, 5)
        return (worlds, peers, arr[:, 0].astype(np.int32),
                arr[:, 1:4], arr[:, 4])

    def cube_count(self, world: str) -> int:
        w = self._worlds.get(world)
        return 0 if w is None else len(w.cubes)

    def subscription_count(self) -> int:
        return sum(
            len(peers) for w in self._worlds.values() for peers in w.cubes.values()
        )

    # endregion
