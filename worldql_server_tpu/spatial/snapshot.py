"""Subscription-index snapshot/restore.

The reference keeps subscriptions in memory only — a restart loses
every AreaMap and clients must re-subscribe (SURVEY §5
checkpoint/resume: "WorldMap/PeerMap are ephemeral"). That is the
floor, not the ceiling: a server hosting a million device-resident
subscriptions should not need a million re-subscribe round trips after
a rolling restart. This module checkpoints any SpatialBackend's live
rows to one compressed ``.npz`` and restores them through the normal
bulk-load path, so the restored index is indistinguishable from one
built by live traffic (same dedupe, same device layout rules).

The format is backend-agnostic and versioned: world names (json),
peer UUIDs as two u64 columns, and (world_id, cube, peer_id) rows.
Restore validates the version and cube size — a snapshot from a
different grid must never silently load into the wrong geometry.
"""

from __future__ import annotations

import json
import logging
import os
import uuid as uuid_mod

import numpy as np

logger = logging.getLogger(__name__)

_VERSION = 1


def export_rows(backend):
    """→ (worlds, peer_hi, peer_lo, row_wid, row_cube, row_pid): the
    backend's live subscription rows in the portable snapshot layout.
    Each backend implements :meth:`SpatialBackend.export_rows` against
    its own internals; this packs the peer UUIDs into two u64
    columns."""
    worlds, peers, wid, cube, pid = backend.export_rows()

    ints = np.fromiter(
        (p.int for p in peers), dtype=object, count=len(peers)
    ) if peers else np.empty(0, object)
    peer_hi = np.fromiter(
        (int(i) >> 64 for i in ints), np.uint64, count=len(peers)
    )
    peer_lo = np.fromiter(
        (int(i) & ((1 << 64) - 1) for i in ints), np.uint64,
        count=len(peers),
    )
    return worlds, peer_hi, peer_lo, wid, cube, pid


def save_snapshot(backend, path: str) -> int:
    """Write the backend's live subscriptions to ``path`` atomically
    (tmp + rename). Returns the number of rows saved."""
    worlds, peer_hi, peer_lo, wid, cube, pid = export_rows(backend)
    # a path (not a handle) so numpy fully finalizes the zip before
    # returning; the .npz suffix keeps savez from appending its own
    tmp = f"{path}.{os.getpid()}.tmp.npz"
    try:
        np.savez_compressed(
            tmp,
            version=np.int64(_VERSION),
            cube_size=np.int64(backend.cube_size),
            worlds=np.frombuffer(
                json.dumps(worlds).encode(), dtype=np.uint8
            ),
            peer_hi=peer_hi,
            peer_lo=peer_lo,
            row_wid=wid,
            row_cube=cube,
            row_pid=pid,
        )
        os.replace(tmp, path)
    except BaseException:
        # a failed save (disk full, kill) must not litter orphan temps
        # next to the snapshot on every crashing shutdown
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    logger.info(
        "index snapshot: %d rows, %d worlds, %d peers -> %s",
        len(pid), len(worlds), len(peer_hi), path,
    )
    return int(len(pid))


class SnapshotError(ValueError):
    """The snapshot cannot be loaded into this backend (wrong version
    or grid geometry) — callers must not silently serve an empty or
    mis-quantized index."""


def load_snapshot(backend, path: str) -> tuple[int, list[uuid_mod.UUID]]:
    """Restore a snapshot into ``backend`` via its bulk-load path.
    Returns ``(rows restored, peers with restored rows)`` — the caller
    needs the peer set to sweep restored subscriptions whose owners
    never reconnect."""
    with np.load(path) as z:
        version = int(z["version"])
        if version != _VERSION:
            raise SnapshotError(
                f"snapshot version {version}, expected {_VERSION}"
            )
        cube_size = int(z["cube_size"])
        if cube_size != backend.cube_size:
            raise SnapshotError(
                f"snapshot cube_size {cube_size} != backend "
                f"{backend.cube_size} — refusing to load into the "
                "wrong grid"
            )
        worlds = json.loads(bytes(z["worlds"]).decode())
        peer_hi, peer_lo = z["peer_hi"], z["peer_lo"]
        wid, cube, pid = z["row_wid"], z["row_cube"], z["row_pid"]
        # validate shape consistency and every index BEFORE mutating
        # the backend: a malformed row must never restore under the
        # wrong peer (negative pids would silently wrap) or leave a
        # half-loaded index
        if (
            len(peer_hi) != len(peer_lo)
            or not (len(wid) == len(pid) == len(cube))
            or (len(cube) and cube.shape[1:] != (3,))
        ):
            raise SnapshotError("column lengths disagree")
        if len(pid) and (
            int(pid.min()) < 0 or int(pid.max()) >= len(peer_hi)
            or int(wid.min()) < 0 or int(wid.max()) >= len(worlds)
        ):
            raise SnapshotError("row peer/world ids out of range")

    peers = [
        uuid_mod.UUID(int=(int(hi) << 64) | int(lo))
        for hi, lo in zip(peer_hi, peer_lo)
    ]
    restored = 0
    for wid_i, world in enumerate(worlds):
        sel = wid == wid_i
        if not sel.any():
            continue
        restored += backend.bulk_add_subscriptions(
            world, [peers[i] for i in pid[sel]], cube[sel]
        )
    backend.flush()
    logger.info("index snapshot: restored %d rows from %s", restored, path)
    used = sorted(set(int(p) for p in pid))
    return restored, [peers[i] for i in used]
