"""Version string with the git short hash.

The reference embeds the commit hash at build time (build.rs:4-11) and
clap renders ``worldql_server x.y.z (abc1234)``. Python has no build
step, so resolve in order: the ``WQL_GIT_HASH`` environment variable
(stamped into container images at build time, Dockerfile), then a live
``git rev-parse`` against the package checkout, then the bare version.
"""

from __future__ import annotations

import os
import subprocess
from pathlib import Path


def _git(args: list[str], cwd: Path) -> str | None:
    try:
        out = subprocess.run(
            ["git", *args], capture_output=True, text=True, timeout=5,
            cwd=cwd,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    value = out.stdout.strip()
    return value if out.returncode == 0 and value else None


def git_short_hash() -> str | None:
    env = os.environ.get("WQL_GIT_HASH")
    if env:
        return env[:7]
    pkg_root = Path(__file__).resolve().parents[1]
    # Guard against an UNRELATED enclosing repo: a package installed
    # into a venv nested inside someone else's checkout would otherwise
    # stamp that project's HEAD. Only report a hash when the repo
    # toplevel is exactly the directory containing this package (the
    # source-checkout layout).
    top = _git(["rev-parse", "--show-toplevel"], pkg_root)
    if top is None or Path(top).resolve() != pkg_root.parent:
        return None
    return _git(["rev-parse", "--short=7", "HEAD"], pkg_root)


def full_version(base: str) -> str:
    hash_ = git_short_hash()
    return f"{base} ({hash_})" if hash_ else base
