"""Per-packet trace channel.

Rebuild of the reference's ``trace_packet!`` macro
(utils/trace_packet.rs:1-7): every inbound packet can be dumped in
full for protocol debugging, and the channel costs one predictable
branch per message when off (the reference compiles it out entirely;
Python's equivalent is a module-level flag checked before any
formatting work happens — the message is never stringified unless
enabled).

Enable with ``-v -v -v`` (main.rs:54-65: verbosity 3 = trace) or
``WQL_TRACE_PACKETS=1``. Records land on the
``worldql_server_tpu.packets`` logger at the custom TRACE level (5,
below DEBUG) so they can be filtered or shipped independently of
application logs.
"""

from __future__ import annotations

import logging
import os

TRACE_LEVEL = 5

logging.addLevelName(TRACE_LEVEL, "TRACE")

_log = logging.getLogger("worldql_server_tpu.packets")

_enabled = os.environ.get("WQL_TRACE_PACKETS") == "1"


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def trace_packet(message) -> None:
    """Dump one packet. The guard runs before any formatting, so the
    disabled path does no work beyond this call + branch."""
    if _enabled:
        _log.log(TRACE_LEVEL, "%s", message)
