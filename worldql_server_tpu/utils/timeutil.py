"""Epoch-millisecond parsing for RecordRead "after" filters.

Matches the reference (worldql_server/src/utils/time.rs:6-16): the
parameter is a stringified *unsigned* integer count of milliseconds
since the Unix epoch; anything else (sign, whitespace, separators)
raises ``ValueError``.
"""

from __future__ import annotations

from datetime import datetime, timedelta, timezone

_EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)

# u64::MAX — the reference parses with .parse::<u64>()
_U64_MAX = 2**64 - 1


def parse_epoch_millis(value: str) -> datetime:
    # ASCII digits only, like Rust's parse::<u64>() — rejects '', signs,
    # whitespace, '_' and non-ASCII Unicode digits.
    if not (value.isascii() and value.isdigit()):
        raise ValueError(f"invalid epoch millis: {value!r}")

    millis = int(value)
    if millis > _U64_MAX:
        raise ValueError(f"epoch millis out of range: {value!r}")

    secs, ms = divmod(millis, 1000)
    try:
        return _EPOCH + timedelta(seconds=secs, milliseconds=ms)
    except OverflowError as exc:
        raise ValueError(f"epoch millis out of range: {value!r}") from exc
