"""Minimal ``.env`` loader.

The reference loads a dotenv file before parsing args (main.rs:51), so
``WQL_*`` fallbacks work from a file as well as the live environment.
No third-party dependency: the dialect is the common intersection —
``KEY=VALUE`` lines, ``#`` comments, optional ``export`` prefix,
single/double quotes stripped, no interpolation. Existing environment
variables always win (dotenv-rs semantics: ``dotenv()`` never
overrides).
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_QUOTES = ("'", '"')


def parse_dotenv(text: str) -> dict[str, str]:
    out: dict[str, str] = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("export "):
            line = line[len("export "):].lstrip()
        key, sep, value = line.partition("=")
        key = key.strip()
        if not sep or not key or any(c.isspace() for c in key):
            logger.warning(".env line %d ignored: %r", lineno, raw)
            continue
        value = value.strip()
        if value[:1] in _QUOTES:
            quote = value[0]
            end = value.find(quote, 1)
            if end < 0:
                logger.warning(".env line %d ignored: %r", lineno, raw)
                continue
            # anything after the closing quote (e.g. a comment) drops
            value = value[1:end]
        else:
            # unquoted values: strip trailing comments
            value = value.split(" #", 1)[0].rstrip()
        out[key] = value
    return out


def load_dotenv(path: str = ".env") -> int:
    """Load ``path`` into ``os.environ`` (existing vars win). Returns
    the number of variables actually set; a missing file is fine."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except (FileNotFoundError, IsADirectoryError):
        return 0
    loaded = 0
    for key, value in parse_dotenv(text).items():
        if key not in os.environ:
            os.environ[key] = value
            loaded += 1
    return loaded
