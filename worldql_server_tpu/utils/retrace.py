"""Jit-retrace tripwire for the batched fan-out engine.

A tick that retraces is a tick that recompiles — tens of milliseconds
to seconds inside a 5 ms budget (the unexplained 207-second depth-2
outlier in BENCH_r05 is the failure mode at its worst). The engine's
defense is capacity tiers: every dynamic dimension (query batch, CSR
slot budget, delta rows) is padded to a power-of-two tier so steady
traffic reuses a handful of compiled variants. This module makes that
property *testable*: every jitted hot-path kernel registers here, the
guard reads each callable's compile-cache size, and the suite fails if
a workload that should stay inside one tier grows the cache past its
budget (``tests/test_retrace_budget.py``; knob: ``WQL_RETRACE_BUDGET``).

Registration is passive — a dict of references, no wrapping, no
overhead on the call path — so it is always on; *counting* only happens
when a test (or an operator, via ``GUARD.counts()``) asks.
"""

from __future__ import annotations

import os

__all__ = [
    "DEFAULT_BUDGET",
    "GUARD",
    "RetraceBudgetExceeded",
    "RetraceGuard",
]


def _default_budget() -> int:
    """Max NEW compiled variants a steady-state workload may add per
    kernel family (``WQL_RETRACE_BUDGET`` overrides)."""
    try:
        return int(os.environ.get("WQL_RETRACE_BUDGET", "2"))
    except ValueError:
        return 2


DEFAULT_BUDGET = _default_budget()


class RetraceBudgetExceeded(AssertionError):
    """A jitted hot-path kernel family exceeded its retrace budget."""


class RetraceGuard:
    """Counts compiled variants per named kernel family.

    A *family* is one logical kernel (e.g. ``tpu_backend.match_run_csr``)
    that may be realized by several jit objects (the sharded backend
    builds one per static config); the family count is the sum of their
    compile-cache sizes, so both "same jit retraced" and "yet another
    jit object built" show up as growth.
    """

    def __init__(self) -> None:
        self._families: dict[str, list] = {}

    def register(self, family: str, fn):
        """Track a jitted callable under ``family``. Idempotent by
        identity; returns ``fn`` so it can wrap a definition."""
        fns = self._families.setdefault(family, [])
        if not any(f is fn for f in fns):
            fns.append(fn)
        return fn

    @staticmethod
    def _traces(fn) -> int:
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            return 0
        try:
            return int(probe())
        except Exception:  # backend without a cache probe: count 0
            return 0

    def counts(self) -> dict[str, int]:
        """Compiled-variant count per family, right now."""
        return {
            family: sum(self._traces(f) for f in fns)
            for family, fns in self._families.items()
        }

    def snapshot(self) -> dict[str, int]:
        return self.counts()

    def delta(self, since: dict[str, int]) -> dict[str, int]:
        """Families that gained compiled variants since ``since``."""
        return {
            family: grown
            for family, count in self.counts().items()
            if (grown := count - since.get(family, 0)) > 0
        }

    def check(
        self,
        budget: int | dict[str, int] | None = None,
        *,
        since: dict[str, int] | None = None,
    ) -> dict[str, int]:
        """Fail if any family grew past its budget.

        ``budget`` is a per-family cap (int for all, or dict overrides;
        default ``DEFAULT_BUDGET``). With ``since`` the cap applies to
        growth after that snapshot — the steady-state tripwire; without
        it, to the absolute count — a warmup-wide ceiling. Returns the
        measured (delta) counts on success.
        """
        counts = self.delta(since) if since is not None else self.counts()

        def cap(family: str) -> int:
            if isinstance(budget, dict):
                return budget.get(family, DEFAULT_BUDGET)
            return DEFAULT_BUDGET if budget is None else budget

        over = {
            family: (n, cap(family))
            for family, n in counts.items()
            if n > cap(family)
        }
        if over:
            lines = ", ".join(
                f"{family}: {n} > budget {c}" for family, (n, c) in over.items()
            )
            raise RetraceBudgetExceeded(
                f"jit retrace budget exceeded — {lines}. A hot-path "
                "kernel is being re-traced (shape churn outside the "
                "padded capacity tiers, or a jit rebuilt per tick); "
                "see utils/retrace.py"
            )
        return counts


#: process-wide guard the backends register their kernels with
GUARD = RetraceGuard()
