"""World-name sanitization.

World names become schema identifiers in the record store, so this is a
security-critical gate. Semantics match the reference
(worldql_server/src/utils/world_names.rs:54-87): names must start with a
letter, may contain ``[A-Za-z0-9_ /\\:@]``, are at most 63 chars *after*
replacement, and the characters space, ``/``, ``\\``, ``:`` and ``@``
are rewritten to ``_``, ``_fs_``, ``_bs_``, ``_cl_`` and ``_at_``.
The literal world ``@global`` is a reserved sentinel and never valid as
a storage/subscription world name.
"""

from __future__ import annotations

import enum
import re

GLOBAL_WORLD = "@global"

_MAX_NAME_LENGTH = 63

_VALID_START = re.compile(r"[A-Za-z]")
_VALID_CHARS = re.compile(r"[A-Za-z0-9_ /\\:@]*\Z")

_REPLACEMENTS = (
    (" ", "_"),
    ("/", "_fs_"),
    ("\\", "_bs_"),
    (":", "_cl_"),
    ("@", "_at_"),
)


class SanitizeErrorKind(enum.Enum):
    IS_GLOBAL_WORLD = "is global world"
    ZERO_LENGTH = "world name must be 1 or more characters long"
    INVALID_START = "must start with a-z or A-Z"
    INVALID_CHARS = "contains invalid characters"
    TOO_LONG = "world name is too long"


class SanitizeError(ValueError):
    def __init__(self, kind: SanitizeErrorKind):
        super().__init__(kind.value)
        self.kind = kind


def sanitize_world_name(world_name: str) -> str:
    """Validate and normalise a world name, or raise :class:`SanitizeError`.

    The length check runs on the *replaced* name, matching the reference
    (world_names.rs:76-84), so e.g. 20 colons expand past the limit.
    """
    if world_name == GLOBAL_WORLD:
        raise SanitizeError(SanitizeErrorKind.IS_GLOBAL_WORLD)

    if not world_name:
        raise SanitizeError(SanitizeErrorKind.ZERO_LENGTH)

    if not _VALID_START.match(world_name[0]):
        raise SanitizeError(SanitizeErrorKind.INVALID_START)

    if not _VALID_CHARS.match(world_name):
        raise SanitizeError(SanitizeErrorKind.INVALID_CHARS)

    for src, dst in _REPLACEMENTS:
        world_name = world_name.replace(src, dst)

    if len(world_name) > _MAX_NAME_LENGTH:
        raise SanitizeError(SanitizeErrorKind.TOO_LONG)

    return world_name
