from .names import GLOBAL_WORLD, SanitizeError, sanitize_world_name
from .rounding import round_by_multiple
from .timeutil import parse_epoch_millis

__all__ = [
    "GLOBAL_WORLD",
    "SanitizeError",
    "sanitize_world_name",
    "round_by_multiple",
    "parse_epoch_millis",
]
