"""Ceil-to-multiple rounding used by the subscription-cube quantizer.

Semantics match the reference (worldql_server/src/utils/round.rs:1-13),
including the special case that exact zero rounds *up* to ``multiple``.
"""

from __future__ import annotations

import math


def round_by_multiple(n: float, multiple: float) -> float:
    if multiple == 0.0:
        return n

    # Special case: 0 rounds up to the multiple.
    if n == 0.0:
        return multiple

    q = n / multiple
    if not math.isfinite(q):
        return q * multiple  # NaN/±inf propagate, like Rust f64::ceil
    return math.ceil(q) * multiple
