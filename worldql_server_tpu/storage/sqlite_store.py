"""SQLite RecordStore: the default self-contained persistent store.

Same observable contract as the reference's Postgres DatabaseClient
(worldql_server/src/database/client.rs) — append-only inserts with
dedupe-on-read, region-scoped reads, read-repair deletes, lazy DDL —
mapped onto SQLite: the reference's schema-per-world + table-per-suffix
(``w_<world>.t_<n>``, query_constants.rs:84-121) becomes table
``w_<world>__t_<n>`` (SQLite has no schemas), with the same btree index
on region_id and the same navigation mapping.

sqlite3 is synchronous; every operation runs on the event loop's
default executor via ``asyncio.to_thread`` under a store-wide lock
(the reference likewise serializes on one DatabaseClient instance,
thread.rs:151-155).
"""

from __future__ import annotations

import asyncio
import logging
import sqlite3
import uuid as uuid_mod
from datetime import datetime, timezone

from ..protocol.types import Record, Vector3
from .sql_common import LruCache, RegionMath, world_key
from .store import DedupeOp, RecordStore, StoredRecord

logger = logging.getLogger(__name__)

_NAV_DDL = (
    """CREATE TABLE IF NOT EXISTS navigation_tables (
        world_name TEXT NOT NULL,
        tx INTEGER NOT NULL, ty INTEGER NOT NULL, tz INTEGER NOT NULL,
        table_suffix INTEGER PRIMARY KEY AUTOINCREMENT,
        UNIQUE (world_name, tx, ty, tz)
    )""",
    """CREATE TABLE IF NOT EXISTS navigation_regions (
        world_name TEXT NOT NULL,
        rx INTEGER NOT NULL, ry INTEGER NOT NULL, rz INTEGER NOT NULL,
        region_id INTEGER PRIMARY KEY AUTOINCREMENT,
        UNIQUE (world_name, rx, ry, rz)
    )""",
)


def _data_table(world: str, suffix: int) -> str:
    # world is sanitized ([A-Za-z][A-Za-z0-9_]*), suffix is an int from
    # our own navigation table — both safe as identifiers.
    return f"w_{world}__t_{suffix}"


class SqliteRecordStore(RecordStore):
    def __init__(self, path: str, config):
        if not path:
            raise ValueError(
                "sqlite:// needs a path (sqlite://records.db); use "
                "memory:// for a non-persistent store"
            )
        self._path = path
        self._math = RegionMath(config)
        cache = config.db_cache_size
        self._table_cache = LruCache(cache)
        self._region_cache = LruCache(cache)
        self._conn: sqlite3.Connection | None = None
        self._lock = asyncio.Lock()

    # region: lifecycle

    async def init(self) -> None:
        def _open():
            conn = sqlite3.connect(self._path, check_same_thread=False)
            conn.execute("PRAGMA journal_mode=WAL")
            for ddl in _NAV_DDL:
                conn.execute(ddl)
            conn.commit()
            return conn

        self._conn = await asyncio.to_thread(_open)

    async def close(self) -> None:
        if self._conn is not None:
            conn, self._conn = self._conn, None
            await asyncio.to_thread(conn.close)

    # endregion

    # region: navigation (lookup-or-insert, LRU-cached; navigation.rs:15-168)

    def _lookup_table_suffix(self, conn, world: str, table: tuple) -> int:
        key = (world, table)
        hit = self._table_cache.get(key)
        if hit is not None:
            return hit
        row = conn.execute(
            "SELECT table_suffix FROM navigation_tables "
            "WHERE world_name=? AND tx=? AND ty=? AND tz=?",
            (world, *table),
        ).fetchone()
        if row is None:
            cur = conn.execute(
                "INSERT INTO navigation_tables (world_name, tx, ty, tz) "
                "VALUES (?,?,?,?)",
                (world, *table),
            )
            suffix = cur.lastrowid
        else:
            suffix = row[0]
        self._table_cache.put(key, suffix)
        return suffix

    def _lookup_region_id(self, conn, world: str, region: tuple) -> int:
        key = (world, region)
        hit = self._region_cache.get(key)
        if hit is not None:
            return hit
        row = conn.execute(
            "SELECT region_id FROM navigation_regions "
            "WHERE world_name=? AND rx=? AND ry=? AND rz=?",
            (world, *region),
        ).fetchone()
        if row is None:
            cur = conn.execute(
                "INSERT INTO navigation_regions (world_name, rx, ry, rz) "
                "VALUES (?,?,?,?)",
                (world, *region),
            )
            region_id = cur.lastrowid
        else:
            region_id = row[0]
        self._region_cache.put(key, region_id)
        return region_id

    def _lookup_ids(self, conn, world: str, position: Vector3) -> tuple[int, int]:
        region = self._math.region_of(position)
        suffix = self._lookup_table_suffix(conn, world, self._math.table_of(region))
        region_id = self._lookup_region_id(conn, world, region)
        return suffix, region_id

    # endregion

    # region: data tables (lazy DDL on missing table; client.rs:178-225)

    def _create_data_table(self, conn, table: str) -> None:
        conn.execute(
            f"""CREATE TABLE IF NOT EXISTS {table} (
                last_modified REAL NOT NULL,
                region_id INTEGER NOT NULL,
                x REAL NOT NULL, y REAL NOT NULL, z REAL NOT NULL,
                uuid TEXT NOT NULL,
                data TEXT,
                flex BLOB
            )"""
        )
        conn.execute(
            f"CREATE INDEX IF NOT EXISTS idx_{table}_region "
            f"ON {table} (region_id)"
        )

    # endregion

    # region: record ops

    async def insert_records(self, records: list[Record]) -> int:
        async with self._lock:
            return await asyncio.to_thread(self._insert_sync, records)

    def _insert_sync(self, records: list[Record]) -> int:
        conn = self._conn
        now = datetime.now(timezone.utc).timestamp()
        # Group rows per data table, one multi-row INSERT each
        # (client.rs:119-162).
        table_map: dict[str, list[tuple]] = {}
        for record in records:
            if record.position is None:
                logger.warning("record %s has no position, skipping", record.uuid)
                continue
            try:
                world = world_key(record.world_name)
            except Exception as exc:
                logger.warning("record %s bad world name: %s", record.uuid, exc)
                continue
            suffix, region_id = self._lookup_ids(conn, world, record.position)
            table_map.setdefault(_data_table(world, suffix), []).append((
                now, region_id,
                record.position.x, record.position.y, record.position.z,
                str(record.uuid), record.data, record.flex,
            ))

        written = 0
        try:
            for table, rows in table_map.items():
                sql = (f"INSERT INTO {table} "
                       "(last_modified, region_id, x, y, z, uuid, data, flex) "
                       "VALUES (?,?,?,?,?,?,?,?)")
                try:
                    conn.executemany(sql, rows)
                except sqlite3.OperationalError as exc:
                    if "no such table" not in str(exc):
                        raise
                    self._create_data_table(conn, table)
                    conn.executemany(sql, rows)
                written += len(rows)
        except Exception:
            # Drop cached ids that may refer to the aborted transaction's
            # navigation inserts, then abandon the partial batch so the
            # next unrelated commit can't persist it. Caches first: a
            # rollback() that itself raises must not leave them stale.
            self._table_cache.clear()
            self._region_cache.clear()
            conn.rollback()
            raise
        conn.commit()
        return written

    async def get_records_in_region(
        self, world_name: str, position: Vector3, after: datetime | None = None
    ) -> list[StoredRecord]:
        async with self._lock:
            return await asyncio.to_thread(
                self._get_sync, world_name, position, after
            )

    def _get_sync(self, world_name, position, after) -> list[StoredRecord]:
        conn = self._conn
        world = world_key(world_name)
        suffix, region_id = self._lookup_ids(conn, world, position)
        conn.commit()  # persist any navigation inserts from the lookup
        table = _data_table(world, suffix)
        sql = (f"SELECT last_modified, x, y, z, uuid, data, flex FROM {table} "
               "WHERE region_id=?")
        params: list = [region_id]
        if after is not None:
            sql += " AND last_modified > ?"
            params.append(after.timestamp())
        try:
            rows = conn.execute(sql, params).fetchall()
        except sqlite3.OperationalError as exc:
            if "no such table" in str(exc):
                return []  # never-written region (client.rs:341-346)
            raise
        return [
            StoredRecord(
                timestamp=datetime.fromtimestamp(ts, timezone.utc),
                record=Record(
                    uuid=uuid_mod.UUID(u),
                    position=Vector3(x, y, z),
                    world_name=world_name,
                    data=data,
                    flex=flex,
                ),
            )
            for ts, x, y, z, u, data, flex in rows
        ]

    async def export_world_records(self, world_name: str) -> list[StoredRecord]:
        async with self._lock:
            return await asyncio.to_thread(self._export_world_sync, world_name)

    def _export_world_sync(self, world_name: str) -> list[StoredRecord]:
        conn = self._conn
        world = world_key(world_name)
        suffixes = [
            row[0] for row in conn.execute(
                "SELECT table_suffix FROM navigation_tables "
                "WHERE world_name=?", (world,),
            ).fetchall()
        ]
        out: list[StoredRecord] = []
        for suffix in suffixes:
            table = _data_table(world, suffix)
            try:
                rows = conn.execute(
                    f"SELECT last_modified, x, y, z, uuid, data, flex "
                    f"FROM {table}"
                ).fetchall()
            except sqlite3.OperationalError as exc:
                if "no such table" in str(exc):
                    continue  # navigation row without a data table yet
                raise
            out.extend(
                StoredRecord(
                    timestamp=datetime.fromtimestamp(ts, timezone.utc),
                    record=Record(
                        uuid=uuid_mod.UUID(u),
                        position=Vector3(x, y, z),
                        world_name=world_name,
                        data=data,
                        flex=flex,
                    ),
                )
                for ts, x, y, z, u, data, flex in rows
            )
        return out

    async def delete_records(self, records: list[Record]) -> int:
        async with self._lock:
            return await asyncio.to_thread(self._delete_sync, records)

    def _delete_sync(self, records: list[Record]) -> int:
        conn = self._conn
        deleted = 0
        for record in records:
            if record.position is None:
                continue
            try:
                world = world_key(record.world_name)
            except Exception as exc:
                logger.warning("record %s bad world name: %s", record.uuid, exc)
                continue
            suffix, region_id = self._lookup_ids(conn, world, record.position)
            table = _data_table(world, suffix)
            try:
                cur = conn.execute(
                    f"DELETE FROM {table} WHERE uuid=? AND region_id=?",
                    (str(record.uuid), region_id),
                )
                deleted += cur.rowcount
            except sqlite3.OperationalError as exc:
                if "no such table" not in str(exc):
                    raise
        conn.commit()
        return deleted

    async def dedupe_records(self, ops: list[DedupeOp]) -> int:
        async with self._lock:
            return await asyncio.to_thread(self._dedupe_sync, ops)

    def _dedupe_sync(self, ops: list[DedupeOp]) -> int:
        conn = self._conn
        deleted = 0
        for rec_uuid, keep_ts, world_name, position in ops:
            world = world_key(world_name)
            suffix, region_id = self._lookup_ids(conn, world, position)
            table = _data_table(world, suffix)
            try:
                cur = conn.execute(
                    f"DELETE FROM {table} "
                    "WHERE uuid=? AND region_id=? AND last_modified < ?",
                    (str(rec_uuid), region_id, keep_ts.timestamp()),
                )
                deleted += cur.rowcount
            except sqlite3.OperationalError as exc:
                if "no such table" not in str(exc):
                    raise
        conn.commit()
        return deleted

    # endregion
