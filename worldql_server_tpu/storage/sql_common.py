"""Shared core for SQL record stores: geo-sharded navigation.

Rebuilds the reference's two-level sharding scheme
(worldql_server/src/database/{world_region,navigation}.rs):

* a position floors to a **region** cell of (x, y, z) sizes
  (world_region.rs:93-110 — see spatial/quantize.clamp_region_coord);
* regions group into **tables** of ``table_size`` extent per axis
  (world_region.rs:38-59);
* ``navigation`` tables map (world, bounds) → serial ``table_suffix`` /
  ``region_id`` (query_constants.rs:2-38), cached in LRUs sized by
  ``db_cache_size`` (0 = unbounded; navigation.rs:30-34, args.rs:57-61);
* data rows live in per-(world, table) tables named from the sanitized
  world name — safety rests on ``sanitize_world_name`` exactly like the
  reference (world_names.rs:54-87).
"""

from __future__ import annotations

from collections import OrderedDict

from ..protocol.types import Vector3
from ..spatial.quantize import region_coords, table_bounds
from ..utils.names import sanitize_world_name


class LruCache:
    """Minimal LRU; ``maxsize=0`` means unbounded (navigation.rs:30-34)."""

    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self._map: OrderedDict = OrderedDict()

    def get(self, key):
        try:
            self._map.move_to_end(key)
            return self._map[key]
        except KeyError:
            return None

    def put(self, key, value) -> None:
        # instances are per-store, never shared across domains: the
        # postgres store's caches live entirely on the event loop, the
        # sqlite store's are only touched inside to_thread hops that
        # its store-wide asyncio.Lock serializes (one hop at a time,
        # ordering published by the loop's executor handoff)
        self._map[key] = value  # wql: allow(unlocked-shared-write)
        self._map.move_to_end(key)
        if self.maxsize and len(self._map) > self.maxsize:
            self._map.popitem(last=False)

    def clear(self) -> None:
        """Drop every entry — used after a rolled-back transaction may
        have cached ids from uncommitted navigation inserts."""
        self._map.clear()


class RegionMath:
    """Position → (region cell, table cell) quantization."""

    def __init__(self, config):
        self.rx = config.db_region_x_size
        self.ry = config.db_region_y_size
        self.rz = config.db_region_z_size
        self.table_size = config.db_table_size

    def region_of(self, position: Vector3) -> tuple[int, int, int]:
        return region_coords(
            position.x, position.y, position.z, self.rx, self.ry, self.rz
        )

    def table_of(self, region: tuple[int, int, int]) -> tuple[int, int, int]:
        return (
            table_bounds(region[0], self.table_size)[0],
            table_bounds(region[1], self.table_size)[0],
            table_bounds(region[2], self.table_size)[0],
        )


def world_key(world_name: str) -> str:
    """Sanitized world name — the only value ever spliced into SQL
    identifiers (world_names.rs gate)."""
    return sanitize_world_name(world_name)
