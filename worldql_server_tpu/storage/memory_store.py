"""In-memory RecordStore: the semantic reference for tests.

Implements the exact append/region-read/dedupe contract of store.py
with plain dicts keyed by (world, region cell). Timestamps default to
``datetime.now(UTC)`` at insert, like the DB's ``NOW()`` column default
(database/query_constants.rs:92).
"""

from __future__ import annotations

import itertools
import logging
from collections import defaultdict
from datetime import datetime, timezone

from ..protocol.types import Record, Vector3
from ..spatial.quantize import region_coords
from ..utils.names import sanitize_world_name
from .store import DedupeOp, RecordStore, StoredRecord

logger = logging.getLogger(__name__)


class MemoryRecordStore(RecordStore):
    def __init__(self, config):
        self._rx = config.db_region_x_size
        self._ry = config.db_region_y_size
        self._rz = config.db_region_z_size
        # (world, (rx, ry, rz)) -> list of (seq, StoredRecord)
        self._regions: dict[tuple, list[tuple[int, StoredRecord]]] = defaultdict(list)
        self._seq = itertools.count()

    def _region_key(self, world_name: str, position: Vector3) -> tuple:
        world = sanitize_world_name(world_name)
        region = region_coords(
            position.x, position.y, position.z, self._rx, self._ry, self._rz
        )
        return (world, region)

    async def insert_records(self, records: list[Record]) -> int:
        written = 0
        now = datetime.now(timezone.utc)
        for record in records:
            if record.position is None:
                logger.warning("record %s has no position, skipping", record.uuid)
                continue
            key = self._region_key(record.world_name, record.position)
            self._regions[key].append(
                (next(self._seq), StoredRecord(now, record))
            )
            written += 1
        return written

    async def get_records_in_region(
        self, world_name: str, position: Vector3, after: datetime | None = None
    ) -> list[StoredRecord]:
        key = self._region_key(world_name, position)
        rows = self._regions.get(key, [])
        out = [sr for _, sr in rows]
        if after is not None:
            out = [sr for sr in out if sr.timestamp > after]
        return list(out)

    async def delete_records(self, records: list[Record]) -> int:
        deleted = 0
        for record in records:
            if record.position is None:
                continue
            key = self._region_key(record.world_name, record.position)
            rows = self._regions.get(key)
            if not rows:
                continue
            keep = [(s, sr) for s, sr in rows if sr.record.uuid != record.uuid]
            deleted += len(rows) - len(keep)
            self._regions[key] = keep
        return deleted

    async def export_world_records(self, world_name: str) -> list[StoredRecord]:
        world = sanitize_world_name(world_name)
        out = []
        for (key_world, _region), rows in self._regions.items():
            if key_world != world:
                continue
            out.extend(sr for _, sr in rows)
        return out

    async def dedupe_records(self, ops: list[DedupeOp]) -> int:
        deleted = 0
        for rec_uuid, keep_ts, world_name, position in ops:
            key = self._region_key(world_name, position)
            rows = self._regions.get(key)
            if not rows:
                continue
            keep = [
                (s, sr)
                for s, sr in rows
                if sr.record.uuid != rec_uuid or sr.timestamp >= keep_ts
            ]
            deleted += len(rows) - len(keep)
            self._regions[key] = keep
        return deleted
