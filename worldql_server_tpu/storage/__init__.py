from .store import DedupeOp, RecordStore, StoredRecord, open_store
from .memory_store import MemoryRecordStore

__all__ = [
    "DedupeOp",
    "RecordStore",
    "StoredRecord",
    "MemoryRecordStore",
    "open_store",
]
