"""PostgreSQL RecordStore — the reference's native dialect.

Faithful rebuild of DatabaseClient (worldql_server/src/database/):
schema ``w_<world>`` per world, data table ``t_<suffix>`` per table
cell with a btree index on region_id (query_constants.rs:84-121),
``navigation.tables``/``navigation.regions`` mapping bounds to serial
ids (query_constants.rs:2-38), lazy DDL on UNDEFINED_TABLE with retry
(client.rs:178-225), and idempotent ``init_database`` (init.rs:10-26).

Drivers: prefers ``asyncpg`` then ``psycopg`` when installed (binary
protocol); otherwise falls back to the built-in pure-Python v3 wire
client (``storage/pgwire.py``) so ``postgres://`` works with zero
dependencies. The logic is kept driver-thin behind ``_exec``/``_fetch``
so all three slot in identically.
"""

from __future__ import annotations

import logging
import re
import uuid as uuid_mod
from datetime import datetime, timezone

from ..protocol.types import Record, Vector3
from .sql_common import LruCache, RegionMath, world_key
from .store import DedupeOp, RecordStore, StoredRecord

logger = logging.getLogger(__name__)


def _load_driver():
    try:
        import asyncpg  # type: ignore

        return "asyncpg", asyncpg
    except ImportError:
        pass
    try:
        import psycopg  # type: ignore

        return "psycopg", psycopg
    except ImportError:
        pass
    # Built-in pure-Python v3 wire driver (storage/pgwire.py): always
    # available, asyncpg-shaped surface, text protocol. The external
    # drivers stay preferred for their binary-protocol performance.
    from . import pgwire

    return "pgwire", pgwire


_NAV_DDL = (
    "CREATE SCHEMA IF NOT EXISTS navigation",
    """CREATE TABLE IF NOT EXISTS navigation.tables (
        world_name varchar NOT NULL,
        tx bigint NOT NULL, ty bigint NOT NULL, tz bigint NOT NULL,
        table_suffix serial NOT NULL,
        UNIQUE (world_name, tx, ty, tz)
    )""",
    """CREATE TABLE IF NOT EXISTS navigation.regions (
        world_name varchar NOT NULL,
        rx bigint NOT NULL, ry bigint NOT NULL, rz bigint NOT NULL,
        region_id serial NOT NULL,
        UNIQUE (world_name, rx, ry, rz)
    )""",
)

UNDEFINED_TABLE = "42P01"

# 7 bind params per row; stay well under the wire protocol's 32767
# int16 parameter-count limit per statement.
_INSERT_CHUNK_ROWS = 4000

_PLACEHOLDER = re.compile(r"\$\d+")


def _psycopg_placeholders(sql: str) -> str:
    """asyncpg-style $N params → psycopg %s (positional order matches)."""
    return _PLACEHOLDER.sub("%s", sql)


class PostgresRecordStore(RecordStore):
    def __init__(self, url: str, config):
        self._driver_name, self._driver = _load_driver()
        self._url = url
        self._math = RegionMath(config)
        cache = config.db_cache_size
        self._table_cache = LruCache(cache)
        self._region_cache = LruCache(cache)
        self._conn = None

    # region: lifecycle

    async def init(self) -> None:
        if self._driver_name in ("asyncpg", "pgwire"):
            self._conn = await self._driver.connect(self._url)
        else:  # psycopg (async API)
            self._conn = await self._driver.AsyncConnection.connect(
                self._url, autocommit=True
            )
        for ddl in _NAV_DDL:
            await self._exec(ddl)

    async def close(self) -> None:
        if self._conn is not None:
            conn, self._conn = self._conn, None
            await conn.close()

    # endregion

    # region: driver shims

    async def _exec(self, sql: str, *params) -> str:
        if self._driver_name in ("asyncpg", "pgwire"):
            return await self._conn.execute(sql, *params)
        async with self._conn.cursor() as cur:
            await cur.execute(_psycopg_placeholders(sql), params)
            return str(cur.rowcount)

    async def _fetch(self, sql: str, *params) -> list:
        if self._driver_name in ("asyncpg", "pgwire"):
            return await self._conn.fetch(sql, *params)
        async with self._conn.cursor() as cur:
            await cur.execute(_psycopg_placeholders(sql), params)
            return await cur.fetchall()

    def _is_undefined_table(self, exc: Exception) -> bool:
        code = getattr(exc, "sqlstate", None) or getattr(exc, "pgcode", None)
        return code == UNDEFINED_TABLE or "does not exist" in str(exc)

    # endregion

    # region: navigation

    async def _lookup_table_suffix(self, world: str, table: tuple) -> int:
        key = (world, table)
        hit = self._table_cache.get(key)
        if hit is not None:
            return hit
        rows = await self._fetch(
            "SELECT table_suffix FROM navigation.tables "
            "WHERE world_name=$1 AND tx=$2 AND ty=$3 AND tz=$4",
            world, *table,
        )
        if rows:
            suffix = rows[0][0]
        else:
            # Race-safe lookup-or-insert: a concurrent writer may have
            # claimed the cell between SELECT and INSERT.
            rows = await self._fetch(
                "INSERT INTO navigation.tables (world_name, tx, ty, tz) "
                "VALUES ($1,$2,$3,$4) "
                "ON CONFLICT (world_name, tx, ty, tz) DO NOTHING "
                "RETURNING table_suffix",
                world, *table,
            )
            if not rows:
                rows = await self._fetch(
                    "SELECT table_suffix FROM navigation.tables "
                    "WHERE world_name=$1 AND tx=$2 AND ty=$3 AND tz=$4",
                    world, *table,
                )
            suffix = rows[0][0]
        self._table_cache.put(key, suffix)
        return suffix

    async def _lookup_region_id(self, world: str, region: tuple) -> int:
        key = (world, region)
        hit = self._region_cache.get(key)
        if hit is not None:
            return hit
        rows = await self._fetch(
            "SELECT region_id FROM navigation.regions "
            "WHERE world_name=$1 AND rx=$2 AND ry=$3 AND rz=$4",
            world, *region,
        )
        if rows:
            region_id = rows[0][0]
        else:
            rows = await self._fetch(
                "INSERT INTO navigation.regions (world_name, rx, ry, rz) "
                "VALUES ($1,$2,$3,$4) "
                "ON CONFLICT (world_name, rx, ry, rz) DO NOTHING "
                "RETURNING region_id",
                world, *region,
            )
            if not rows:
                rows = await self._fetch(
                    "SELECT region_id FROM navigation.regions "
                    "WHERE world_name=$1 AND rx=$2 AND ry=$3 AND rz=$4",
                    world, *region,
                )
            region_id = rows[0][0]
        self._region_cache.put(key, region_id)
        return region_id

    async def _lookup_ids(self, world: str, position: Vector3) -> tuple[int, int]:
        region = self._math.region_of(position)
        suffix = await self._lookup_table_suffix(world, self._math.table_of(region))
        region_id = await self._lookup_region_id(world, region)
        return suffix, region_id

    # endregion

    # region: data tables

    async def _create_data_table(self, world: str, suffix: int) -> None:
        await self._exec(f'CREATE SCHEMA IF NOT EXISTS "w_{world}"')
        await self._exec(
            f'''CREATE TABLE IF NOT EXISTS "w_{world}".t_{suffix} (
                last_modified timestamptz NOT NULL DEFAULT NOW(),
                region_id int NOT NULL,
                x double precision NOT NULL,
                y double precision NOT NULL,
                z double precision NOT NULL,
                uuid varchar NOT NULL,
                data varchar,
                flex bytea
            )'''
        )
        await self._exec(
            f'CREATE INDEX IF NOT EXISTS t_{suffix}_region '
            f'ON "w_{world}".t_{suffix} (region_id)'
        )

    # endregion

    # region: record ops

    async def insert_records(self, records: list[Record]) -> int:
        table_map: dict[tuple[str, int], list[tuple]] = {}
        for record in records:
            if record.position is None:
                logger.warning("record %s has no position, skipping", record.uuid)
                continue
            try:
                world = world_key(record.world_name)
            except Exception as exc:
                logger.warning("record %s bad world name: %s", record.uuid, exc)
                continue
            suffix, region_id = await self._lookup_ids(world, record.position)
            table_map.setdefault((world, suffix), []).append((
                region_id,
                record.position.x, record.position.y, record.position.z,
                str(record.uuid), record.data, record.flex,
            ))

        written = 0
        for (world, suffix), rows in table_map.items():
            # One multi-row INSERT per table (client.rs:119-162), chunked
            # below PostgreSQL's 32767 bind-parameter ceiling (int16 in
            # the extended protocol): 4000 rows × 7 params = 28000.
            for start in range(0, len(rows), _INSERT_CHUNK_ROWS):
                chunk = rows[start:start + _INSERT_CHUNK_ROWS]
                placeholders = ",".join(
                    "(" + ",".join(f"${i * 7 + j + 1}" for j in range(7)) + ")"
                    for i in range(len(chunk))
                )
                sql = (f'INSERT INTO "w_{world}".t_{suffix} '
                       "(region_id, x, y, z, uuid, data, flex) "
                       f"VALUES {placeholders}")
                params = [v for row in chunk for v in row]
                try:
                    await self._exec(sql, *params)
                except Exception as exc:
                    if not self._is_undefined_table(exc):
                        raise
                    await self._create_data_table(world, suffix)
                    await self._exec(sql, *params)
                written += len(chunk)
        return written

    async def get_records_in_region(
        self, world_name: str, position: Vector3, after: datetime | None = None
    ) -> list[StoredRecord]:
        world = world_key(world_name)
        suffix, region_id = await self._lookup_ids(world, position)
        sql = (f'SELECT last_modified, x, y, z, uuid, data, flex '
               f'FROM "w_{world}".t_{suffix} WHERE region_id=$1')
        params: list = [region_id]
        if after is not None:
            sql += " AND last_modified > $2"
            params.append(after)
        try:
            rows = await self._fetch(sql, *params)
        except Exception as exc:
            if self._is_undefined_table(exc):
                return []
            raise
        out = []
        for ts, x, y, z, u, data, flex in rows:
            if ts.tzinfo is None:
                ts = ts.replace(tzinfo=timezone.utc)
            out.append(StoredRecord(
                timestamp=ts,
                record=Record(
                    uuid=uuid_mod.UUID(u),
                    position=Vector3(x, y, z),
                    world_name=world_name,
                    data=data,
                    flex=bytes(flex) if flex is not None else None,
                ),
            ))
        return out

    async def export_world_records(self, world_name: str) -> list[StoredRecord]:
        world = world_key(world_name)
        suffix_rows = await self._fetch(
            "SELECT table_suffix FROM navigation.tables WHERE world_name=$1",
            world,
        )
        out: list[StoredRecord] = []
        for (suffix,) in suffix_rows:
            try:
                rows = await self._fetch(
                    f'SELECT last_modified, x, y, z, uuid, data, flex '
                    f'FROM "w_{world}".t_{suffix}'
                )
            except Exception as exc:
                if self._is_undefined_table(exc):
                    continue
                raise
            for ts, x, y, z, u, data, flex in rows:
                if ts.tzinfo is None:
                    ts = ts.replace(tzinfo=timezone.utc)
                out.append(StoredRecord(
                    timestamp=ts,
                    record=Record(
                        uuid=uuid_mod.UUID(u),
                        position=Vector3(x, y, z),
                        world_name=world_name,
                        data=data,
                        flex=bytes(flex) if flex is not None else None,
                    ),
                ))
        return out

    async def delete_records(self, records: list[Record]) -> int:
        deleted = 0
        for record in records:
            if record.position is None:
                continue
            try:
                world = world_key(record.world_name)
            except Exception as exc:
                logger.warning("record %s bad world name: %s", record.uuid, exc)
                continue
            suffix, region_id = await self._lookup_ids(world, record.position)
            try:
                status = await self._exec(
                    f'DELETE FROM "w_{world}".t_{suffix} '
                    "WHERE uuid=$1 AND region_id=$2",
                    str(record.uuid), region_id,
                )
                deleted += _rowcount(status)
            except Exception as exc:
                if not self._is_undefined_table(exc):
                    raise
        return deleted

    async def dedupe_records(self, ops: list[DedupeOp]) -> int:
        deleted = 0
        for rec_uuid, keep_ts, world_name, position in ops:
            world = world_key(world_name)
            suffix, region_id = await self._lookup_ids(world, position)
            try:
                status = await self._exec(
                    f'DELETE FROM "w_{world}".t_{suffix} '
                    "WHERE uuid=$1 AND region_id=$2 AND last_modified < $3",
                    str(rec_uuid), region_id, keep_ts,
                )
                deleted += _rowcount(status)
            except Exception as exc:
                if not self._is_undefined_table(exc):
                    raise
        return deleted

    # endregion


def _rowcount(status: str) -> int:
    """asyncpg returns e.g. 'DELETE 3'; psycopg shim returns an int
    string."""
    try:
        return int(str(status).rsplit(" ", 1)[-1])
    except ValueError:
        return 0
