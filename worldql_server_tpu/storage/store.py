"""Record persistence interface.

Capability contract from the reference's DatabaseClient
(worldql_server/src/database/client.rs):

* **Insert is append** — duplicates are tolerated at write time and
  collapsed on read (client.rs:86-228).
* **Region-scoped reads** fetch every row in the DB region containing a
  position, optionally filtered to rows newer than an "after"
  timestamp; reads of never-written regions return empty
  (client.rs:312-362).
* **Read-repair dedupe** — after a read, older duplicate rows per
  record-uuid are deleted (client.rs:402-412, record_read.rs:126-130).
* **Delete** removes all rows for (uuid, world, region)
  (client.rs:365-399).

Region/table sharding follows WorldRegion semantics
(database/world_region.rs): positions quantize to floor-style region
cells of (x, y, z) sizes, grouped into tables of ``table_size`` extent
per axis. Storage backends: SQLite (default, self-contained), memory
(tests), Postgres (when a driver is available).
"""

from __future__ import annotations

import abc
import uuid as uuid_mod
from dataclasses import dataclass
from datetime import datetime

from ..protocol.types import Record, Vector3


@dataclass(slots=True)
class StoredRecord:
    """A record row plus its last-modified timestamp."""

    timestamp: datetime
    record: Record


# (record_uuid, keep_timestamp, world_name, position) — delete older rows
# (database/client.rs:31, record_read.rs:84-97)
DedupeOp = tuple[uuid_mod.UUID, datetime, str, Vector3]


class RecordStore(abc.ABC):
    @abc.abstractmethod
    async def insert_records(self, records: list[Record]) -> int:
        """Append records (no upsert); returns rows written. Records
        without positions are skipped with a warning, like the
        reference (client.rs:102-117)."""

    @abc.abstractmethod
    async def get_records_in_region(
        self, world_name: str, position: Vector3, after: datetime | None = None
    ) -> list[StoredRecord]:
        """All rows in the region containing ``position``; optionally
        only rows with timestamp > ``after``."""

    @abc.abstractmethod
    async def delete_records(self, records: list[Record]) -> int:
        """Delete all rows matching each record's (uuid, world, region);
        returns rows deleted."""

    @abc.abstractmethod
    async def dedupe_records(self, ops: list[DedupeOp]) -> int:
        """Read-repair: delete rows older than the kept timestamp for
        each record uuid; returns rows deleted."""

    async def export_world_records(self, world_name: str) -> list[StoredRecord]:
        """Every row belonging to ``world_name``, across all regions —
        the live-resharding capsule read (one world migrates between
        shards as a unit). Duplicate append rows are returned as-is;
        the importer re-appends them, preserving dedupe-on-read
        semantics on the new owner."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support world export"
        )

    async def init(self) -> None:
        """Idempotent schema/bootstrap (database/init.rs:10-26)."""

    async def close(self) -> None:
        pass


def open_store(url: str, config) -> RecordStore:
    """Create a store from a URL: ``memory://``, ``sqlite://PATH`` or
    ``postgres://...`` (gated on an available driver)."""
    from .memory_store import MemoryRecordStore

    if url.startswith("memory://"):
        return MemoryRecordStore(config)
    if url.startswith("sqlite://"):
        from .sqlite_store import SqliteRecordStore

        return SqliteRecordStore(url[len("sqlite://"):], config)
    if url.startswith(("postgres://", "postgresql://")):
        from .postgres_store import PostgresRecordStore  # raises if no driver

        return PostgresRecordStore(url, config)
    raise ValueError(f"unsupported store url: {url}")
