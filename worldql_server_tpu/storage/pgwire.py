"""Dependency-free PostgreSQL client: frontend/backend protocol v3.

The reference persists through ``tokio-postgres``
(worldql_server/src/database/client.rs); this image ships neither
asyncpg nor psycopg, so this module implements the slice of the v3
wire protocol `PostgresRecordStore` needs directly on ``asyncio``
sockets — making ``postgres://`` URLs work out of the box while still
deferring to asyncpg/psycopg when installed (they keep binary-protocol
performance).

Scope (deliberately minimal, fully standard):
* startup + authentication: trust, cleartext, md5, SCRAM-SHA-256
  (RFC 5802/7677, the default for PostgreSQL >= 14);
* optional TLS via the SSLRequest dance (``?sslmode=require``);
* the SIMPLE QUERY protocol ('Q' → RowDescription/DataRow/
  CommandComplete/ErrorResponse/ReadyForQuery) with text-format
  result decoding by type OID — used for statements without
  parameters (DDL), like tokio-postgres's ``batch_execute``;
* the EXTENDED QUERY protocol (Parse/Bind/Describe/Execute/Sync)
  for every parameterized statement: ``$N`` values travel as typed
  protocol-level parameters — they never enter SQL text, matching
  the reference's injection-safety posture (client.rs:161-162,
  navigation.rs:56-64) — with an LRU-bounded named-statement cache
  so hot statements parse once per connection;
* errors surface as :class:`PgWireError` with ``.sqlstate``, which is
  what the store's UNDEFINED_TABLE lazy-DDL retry path keys on
  (client.rs:178-225).

The surface mirrors asyncpg (``connect`` / ``execute`` / ``fetch`` /
``close``) so `postgres_store` drives all three drivers identically.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import hmac
import os
import re
import ssl as ssl_mod
import struct
from datetime import date, datetime, timedelta, timezone
from urllib.parse import parse_qs, unquote, urlparse

PROTOCOL_V3 = 196608       # 3 << 16
SSL_REQUEST = 80877103

_TS_RE = re.compile(
    r"(\d{4})-(\d{2})-(\d{2})[ T](\d{2}):(\d{2}):(\d{2})"
    r"(?:\.(\d{1,6}))?(?:([+-])(\d{2})(?::?(\d{2}))?)?$"
)


class PgWireError(Exception):
    """Server ErrorResponse. ``fields`` holds the single-letter keyed
    error fields; ``sqlstate`` is field 'C' (e.g. 42P01)."""

    def __init__(self, fields: dict[str, str]):
        self.fields = fields
        super().__init__(
            f"{fields.get('S', 'ERROR')} {fields.get('C', '?????')}: "
            f"{fields.get('M', 'unknown error')}"
        )

    @property
    def sqlstate(self) -> str | None:
        return self.fields.get("C")


# region: literal binding


def quote_literal(value) -> str:
    """One Python value → SQL literal. Standard-conforming quoting:
    only ``'`` doubles; backslashes are plain characters."""
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "TRUE" if value else "FALSE"
    if isinstance(value, int):
        return str(value)
    if isinstance(value, float):
        if value != value:
            return "'NaN'::float8"
        if value in (float("inf"), float("-inf")):
            return f"'{'-' if value < 0 else ''}Infinity'::float8"
        return repr(value)
    if isinstance(value, (bytes, bytearray, memoryview)):
        return f"'\\x{bytes(value).hex()}'::bytea"
    if isinstance(value, datetime):
        return f"'{value.isoformat()}'::timestamptz"
    if isinstance(value, date):
        return f"'{value.isoformat()}'::date"
    if isinstance(value, str):
        return "'" + value.replace("'", "''") + "'"
    raise TypeError(f"cannot bind {type(value).__name__} as SQL literal")


def bind_params(sql: str, params: tuple) -> str:
    """Substitute ``$N`` placeholders with quoted literals. ``$N``
    inside string literals is left alone (the store's SQL never puts
    placeholders in literals, but correctness is cheap: split on
    quotes first)."""
    if not params:
        return sql
    lits = [quote_literal(p) for p in params]

    def sub(m: re.Match) -> str:
        n = int(m.group(1))
        if not 1 <= n <= len(lits):
            raise IndexError(f"${n} out of range for {len(lits)} params")
        return lits[n - 1]

    parts = sql.split("'")
    for i in range(0, len(parts), 2):  # even chunks are outside quotes
        parts[i] = re.sub(r"\$(\d+)", sub, parts[i])
    return "'".join(parts)


# endregion

# region: extended-protocol parameter encoding


def param_oid(value) -> int:
    """Declared parameter type for Parse. Explicit OIDs (rather than 0
    = infer) let the server type-check the Bind values and keep the
    in-process test double's decoding honest. bool must precede int
    (bool is an int subclass)."""
    if value is None:
        return 0                      # NULL carries no type
    if isinstance(value, bool):
        return _OID_BOOL
    if isinstance(value, int):
        return _OID_INT8
    if isinstance(value, float):
        return _OID_FLOAT8
    if isinstance(value, (bytes, bytearray, memoryview)):
        return _OID_BYTEA
    if isinstance(value, datetime):
        return _OID_TIMESTAMPTZ
    if isinstance(value, date):
        return _OID_DATE
    if isinstance(value, str):
        return _OID_TEXT
    raise TypeError(f"cannot bind {type(value).__name__} as parameter")


def param_text(value) -> str | None:
    """One Python value → text-format Bind value (None = SQL NULL)."""
    if value is None:
        return None
    if isinstance(value, bool):
        return "t" if value else "f"
    if isinstance(value, int):
        return str(value)
    if isinstance(value, float):
        if value != value:
            return "NaN"
        if value in (float("inf"), float("-inf")):
            return ("-" if value < 0 else "") + "Infinity"
        return repr(value)
    if isinstance(value, (bytes, bytearray, memoryview)):
        return "\\x" + bytes(value).hex()
    if isinstance(value, (datetime, date)):
        return value.isoformat()
    if isinstance(value, str):
        return value
    raise TypeError(f"cannot bind {type(value).__name__} as parameter")


# endregion

# region: text-format decoding

_OID_BOOL = 16
_OID_BYTEA = 17
_OID_INT8 = 20
_OID_INT2 = 21
_OID_INT4 = 23
_OID_TEXT = 25
_OID_OID = 26
_OID_FLOAT4 = 700
_OID_FLOAT8 = 701
_OID_NUMERIC = 1700
_OID_DATE = 1082
_OID_TIMESTAMP = 1114
_OID_TIMESTAMPTZ = 1184


def _parse_timestamp(text: str):
    m = _TS_RE.match(text)
    if m is None:
        return text  # e.g. 'infinity'
    y, mo, d, h, mi, s = (int(m.group(i)) for i in range(1, 7))
    us = int((m.group(7) or "0").ljust(6, "0"))
    tz = None
    if m.group(8):
        offset = int(m.group(9)) * 3600 + int(m.group(10) or "0") * 60
        tz = timezone.utc if offset == 0 else timezone(
            timedelta(seconds=offset * (-1 if m.group(8) == "-" else 1))
        )
    return datetime(y, mo, d, h, mi, s, us, tz)


def decode_text(oid: int, text: str):
    if oid in (_OID_INT2, _OID_INT4, _OID_INT8, _OID_OID):
        return int(text)
    if oid in (_OID_FLOAT4, _OID_FLOAT8, _OID_NUMERIC):
        return float(text)
    if oid == _OID_BOOL:
        return text == "t"
    if oid == _OID_BYTEA:
        if text.startswith("\\x"):
            return bytes.fromhex(text[2:])
        return text.encode("latin-1")  # legacy escape format
    if oid in (_OID_TIMESTAMP, _OID_TIMESTAMPTZ):
        return _parse_timestamp(text)
    if oid == _OID_DATE:
        y, mo, d = text.split("-")
        return date(int(y), int(mo), int(d))
    return text


# endregion

# region: SCRAM-SHA-256 (RFC 5802 / RFC 7677)


class _Scram:
    def __init__(self, user: str, password: str):
        self._password = password.encode()
        self._nonce = base64.b64encode(os.urandom(18)).decode()
        self.client_first_bare = f"n={user},r={self._nonce}"

    def client_first(self) -> bytes:
        return f"n,,{self.client_first_bare}".encode()

    def client_final(self, server_first: bytes) -> bytes:
        attrs = dict(
            kv.split("=", 1) for kv in server_first.decode().split(",")
        )
        r, salt, i = attrs["r"], base64.b64decode(attrs["s"]), int(attrs["i"])
        if not r.startswith(self._nonce):
            raise PgWireError({"C": "28000", "M": "SCRAM nonce mismatch"})
        salted = hashlib.pbkdf2_hmac("sha256", self._password, salt, i)
        client_key = hmac.digest(salted, b"Client Key", "sha256")
        stored_key = hashlib.sha256(client_key).digest()
        without_proof = f"c={base64.b64encode(b'n,,').decode()},r={r}"
        auth_message = (
            f"{self.client_first_bare},{server_first.decode()},"
            f"{without_proof}"
        ).encode()
        signature = hmac.digest(stored_key, auth_message, "sha256")
        proof = bytes(a ^ b for a, b in zip(client_key, signature))
        self._server_key = hmac.digest(salted, b"Server Key", "sha256")
        self._auth_message = auth_message
        return (
            f"{without_proof},p={base64.b64encode(proof).decode()}"
        ).encode()

    def verify_server_final(self, server_final: bytes) -> None:
        attrs = dict(
            kv.split("=", 1) for kv in server_final.decode().split(",")
        )
        expect = hmac.digest(self._server_key, self._auth_message, "sha256")
        if base64.b64decode(attrs.get("v", "")) != expect:
            raise PgWireError({"C": "28000", "M": "bad server signature"})


# endregion


class PgWireConnection:
    """One server connection: simple-query protocol for parameterless
    statements, extended-query protocol (with a named-statement cache)
    for everything with ``$N`` parameters."""

    #: named-statement cache bound (per connection). The store's hot
    #: statements (navigation lookup/insert, region read, dedupe
    #: delete) are a handful of shapes; multi-row INSERT shapes vary by
    #: row count, so the cache is LRU-bounded rather than unbounded.
    STMT_CACHE_MAX = 64

    def __init__(self, reader, writer, params: dict):
        self._reader = reader
        self._writer = writer
        self._params = params
        self._closed = False
        # one in-flight query cycle per connection: concurrent tasks
        # sharing the connection must serialize, or they interleave
        # reads on the shared stream and cross-wire each other's rows
        # (asyncpg raises InterfaceError here; we just queue)
        self._lock = asyncio.Lock()
        # keyed by (sql, declared param OIDs): Parse freezes the types,
        # so the same SQL bound with different Python types is a
        # different server-side statement
        self._stmts: dict[tuple, str] = {}
        self._stmt_seq = 0
        self._dead_stmts: list[str] = []   # to Close on the next cycle

    # -- connection establishment --

    @classmethod
    async def connect(cls, url: str) -> "PgWireConnection":
        u = urlparse(url)
        if u.scheme not in ("postgres", "postgresql"):
            raise ValueError(f"not a postgres url: {url}")
        host = u.hostname or "localhost"
        port = u.port or 5432
        user = unquote(u.username) if u.username else os.environ.get(
            "PGUSER", "postgres"
        )
        password = unquote(u.password) if u.password else os.environ.get(
            "PGPASSWORD", ""
        )
        database = (u.path or "/").lstrip("/") or user
        q = parse_qs(u.query)
        sslmode = q.get("sslmode", ["prefer"])[0]

        reader, writer = await asyncio.open_connection(host, port)
        try:
            if sslmode in ("require", "verify-ca", "verify-full"):
                writer.write(struct.pack(">ii", 8, SSL_REQUEST))
                await writer.drain()
                answer = await reader.readexactly(1)
                if answer != b"S":
                    raise PgWireError(
                        {"C": "08001", "M": "server refused TLS"}
                    )
                ctx = ssl_mod.create_default_context()
                if sslmode == "require":  # libpq parity: no cert check
                    ctx.check_hostname = False
                    ctx.verify_mode = ssl_mod.CERT_NONE
                elif sslmode == "verify-ca":  # CA yes, hostname no
                    ctx.check_hostname = False
                    ctx.verify_mode = ssl_mod.CERT_REQUIRED
                await writer.start_tls(ctx, server_hostname=host)

            conn = cls(reader, writer, {"user": user, "database": database})
            await conn._startup(user, password, database)
            return conn
        except BaseException:
            # a failed startup/auth must not leak the socket (stores
            # retry connects in a loop — one fd per attempt adds up)
            writer.close()
            raise

    async def _startup(self, user: str, password: str, database: str) -> None:
        body = b""
        for k, v in (("user", user), ("database", database),
                     ("client_encoding", "UTF8")):
            body += k.encode() + b"\0" + v.encode() + b"\0"
        body += b"\0"
        self._writer.write(
            struct.pack(">ii", len(body) + 8, PROTOCOL_V3) + body
        )
        await self._writer.drain()

        scram = None
        while True:
            tag, payload = await self._recv()
            if tag == b"R":
                (code,) = struct.unpack(">i", payload[:4])
                if code == 0:           # AuthenticationOk
                    continue
                if code == 3:           # cleartext
                    self._send(b"p", password.encode() + b"\0")
                elif code == 5:         # md5
                    salt = payload[4:8]
                    inner = hashlib.md5(
                        password.encode() + user.encode()
                    ).hexdigest()
                    digest = hashlib.md5(
                        inner.encode() + salt
                    ).hexdigest()
                    self._send(b"p", b"md5" + digest.encode() + b"\0")
                elif code == 10:        # SASL mechanisms
                    mechs = payload[4:].split(b"\0")
                    if b"SCRAM-SHA-256" not in mechs:
                        raise PgWireError(
                            {"C": "28000",
                             "M": f"unsupported SASL mechanisms {mechs}"}
                        )
                    scram = _Scram(user, password)
                    first = scram.client_first()
                    self._send(
                        b"p",
                        b"SCRAM-SHA-256\0"
                        + struct.pack(">i", len(first)) + first,
                    )
                elif code == 11:        # SASL continue
                    self._send(b"p", scram.client_final(payload[4:]))
                elif code == 12:        # SASL final
                    scram.verify_server_final(payload[4:])
                else:
                    raise PgWireError(
                        {"C": "28000",
                         "M": f"unsupported auth method {code}"}
                    )
                await self._writer.drain()
            elif tag == b"K":           # BackendKeyData
                continue
            elif tag == b"S":           # ParameterStatus
                continue
            elif tag == b"Z":           # ReadyForQuery
                return
            elif tag == b"E":
                raise PgWireError(self._error_fields(payload))
            # NoticeResponse and anything else: ignore

    # -- framing --

    def _send(self, tag: bytes, body: bytes) -> None:
        self._writer.write(tag + struct.pack(">i", len(body) + 4) + body)

    async def _recv(self) -> tuple[bytes, bytes]:
        head = await self._reader.readexactly(5)
        tag = head[:1]
        (length,) = struct.unpack(">i", head[1:5])
        payload = await self._reader.readexactly(length - 4)
        return tag, payload

    @staticmethod
    def _error_fields(payload: bytes) -> dict[str, str]:
        fields: dict[str, str] = {}
        for chunk in payload.split(b"\0"):
            if chunk:
                fields[chr(chunk[0])] = chunk[1:].decode(
                    "utf-8", "replace"
                )
        return fields

    @staticmethod
    def _parse_row_desc(payload: bytes) -> list[int]:
        (ncols,) = struct.unpack(">h", payload[:2])
        oids, off = [], 2
        for _ in range(ncols):
            end = payload.index(b"\0", off)
            oid = struct.unpack(">i", payload[end + 7:end + 11])[0]
            oids.append(oid)
            off = end + 19
        return oids

    @staticmethod
    def _parse_data_row(payload: bytes, oids: list[int]) -> tuple:
        (ncols,) = struct.unpack(">h", payload[:2])
        off, row = 2, []
        for c in range(ncols):
            (ln,) = struct.unpack(">i", payload[off:off + 4])
            off += 4
            if ln == -1:
                row.append(None)
            else:
                text = payload[off:off + ln].decode()
                off += ln
                row.append(decode_text(oids[c], text))
        return tuple(row)

    async def _read_cycle(self) -> tuple[list, str]:
        """Drain one query cycle (either protocol) to ReadyForQuery."""
        rows: list[tuple] = []
        oids: list[int] = []
        tag_line = ""
        error: PgWireError | None = None
        while True:
            tag, payload = await self._recv()
            if tag == b"T":             # RowDescription
                oids = self._parse_row_desc(payload)
            elif tag == b"D":           # DataRow
                rows.append(self._parse_data_row(payload, oids))
            elif tag == b"C":           # CommandComplete
                tag_line = payload.rstrip(b"\0").decode()
            elif tag == b"E":
                error = PgWireError(self._error_fields(payload))
            elif tag == b"Z":           # ReadyForQuery — end of cycle
                if error is not None:
                    raise error
                return rows, tag_line
            # '1' parse / '2' bind / '3' close complete, 'n' no data,
            # 's' portal suspended, 'N' notices, 'I' empty query,
            # 'S' params: ignored

    # -- queries (asyncpg-compatible surface) --

    async def _query(self, sql: str) -> tuple[list, str]:
        """Simple-query protocol: parameterless statements (DDL and
        navigation schema setup — tokio-postgres's batch_execute
        equivalent, client.rs:178-225)."""
        if self._closed:
            raise PgWireError({"C": "08003", "M": "connection is closed"})
        async with self._lock:
            self._send(b"Q", sql.encode() + b"\0")
            await self._writer.drain()
            return await self._read_cycle()

    async def _query_ext(self, sql: str, params: tuple) -> tuple[list, str]:
        """Extended-query protocol: Parse (cached per connection) →
        Bind (typed text-format parameters — values NEVER enter SQL
        text) → Describe → Execute → Sync, pipelined in one flush."""
        if self._closed:
            raise PgWireError({"C": "08003", "M": "connection is closed"})
        oids = tuple(param_oid(p) for p in params)
        key = (sql, oids)
        async with self._lock:
            # names orphaned by an earlier error cycle: Close them on
            # this pipeline (they no longer back any cache entry)
            for dead in self._dead_stmts:
                self._send(b"C", b"S" + dead.encode() + b"\0")
            self._dead_stmts.clear()
            name = self._stmts.pop(key, None)
            new_parse = name is None
            if new_parse:
                # evict LRU entries past the bound; Close rides the
                # same pipeline ahead of the Parse
                while len(self._stmts) >= self.STMT_CACHE_MAX:
                    old_key, old_name = next(iter(self._stmts.items()))
                    del self._stmts[old_key]
                    self._send(b"C", b"S" + old_name.encode() + b"\0")
                self._stmt_seq += 1
                name = f"_wql{self._stmt_seq}"
                body = name.encode() + b"\0" + sql.encode() + b"\0"
                body += struct.pack(">h", len(oids))
                for oid in oids:
                    body += struct.pack(">i", oid)
                self._send(b"P", body)

            bind = b"\0" + name.encode() + b"\0"
            bind += struct.pack(">hh", 1, 0)        # all params text
            bind += struct.pack(">h", len(params))
            for p in params:
                text = param_text(p)
                if text is None:
                    bind += struct.pack(">i", -1)
                else:
                    raw = text.encode()
                    bind += struct.pack(">i", len(raw)) + raw
            bind += struct.pack(">hh", 1, 0)        # all results text
            self._send(b"B", bind)
            self._send(b"D", b"P\0")                # describe portal
            self._send(b"E", b"\0" + struct.pack(">i", 0))
            self._send(b"S", b"")
            await self._writer.drain()
            try:
                result = await self._read_cycle()
            except PgWireError:
                # not re-cached: if the Parse failed the name does not
                # exist server-side; if it parsed but Bind/Execute
                # errored (or a cached statement went bad — 26000
                # after a pooler swap) re-parsing next call is the
                # safe recovery either way. The name may still exist
                # server-side — Close it on the next cycle (Close on
                # a nonexistent statement is a no-op by protocol).
                self._dead_stmts.append(name)
                raise
            self._stmts[key] = name     # (re-)insert at LRU tail
            return result

    async def execute(self, sql: str, *params) -> str:
        if params:
            _, tag_line = await self._query_ext(sql, params)
        else:
            _, tag_line = await self._query(sql)
        return tag_line

    async def fetch(self, sql: str, *params) -> list:
        if params:
            rows, _ = await self._query_ext(sql, params)
        else:
            rows, _ = await self._query(sql)
        return rows

    async def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._send(b"X", b"")
                await self._writer.drain()
            except Exception:
                pass
            self._writer.close()


async def connect(url: str) -> PgWireConnection:
    """asyncpg-style module-level entry point."""
    return await PgWireConnection.connect(url)
