"""Spatial fan-out sharded over a device mesh.

Scale-out design (BASELINE configs 4-5): the sorted base segment is
split into per-device contiguous key ranges — split points snapped to
cube-run boundaries so every cube's subscriber run lives wholly on one
device. Queries shard over the ``batch`` axis. Each device binary-
searches its local range; exactly one ``space`` shard can match a given
cube, so partial [M/b, K] results (−1 = no match) combine with a single
``pmax`` over ``space`` — one ICI collective per tick, no host hops.

The small delta segment (rows added since the last compaction — see
spatial/tpu_backend.py) is *replicated* across the mesh: every device
matches the full delta locally, the partials concatenate with the base
partials before the ``pmax``, and the merge stays one collective.

SPMD via ``jax.shard_map``; XLA lays out the gathers per shard and the
final combine as an ICI all-reduce(max). Worlds need no special
handling: world id is part of the spatial key, so a world's cubes
scatter across shards (load-balancing Zipf-hotspot worlds) while each
cube stays device-local. Sparse / CSR result compaction runs in the
same jit after the shard_map — XLA partitions the cumsum/scatter with
the collectives it needs, so compacted results work identically on the
mesh (the distributed delivery path consumes CSR).

Query arrays enter as numpy with explicit ``in_shardings``, so every
H2D transfer rides the ONE jitted dispatch — no per-array
``device_put`` round-trips (they dominate on tunneled devices).
"""

from __future__ import annotations

import numpy as np

from ..spatial import jaxconf  # noqa: F401  (must precede jax import)
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..spatial.hashing import PAD_KEY, n_distinct, next_pow2, pad_to
from ..utils import retrace
from ..spatial.tpu_backend import (
    CSR_ROW,
    CSR_ROW_B,
    SEG_ARRAYS,
    TpuSpatialBackend,
    _alloc_buffers,
    _grow_buffers,
    _scatter_dead,
    _sort_segment_dev,
    _write_chunk,
    compact_sparse,
    match_core,
    pack_csr,
    probe_buckets_for,
    probe_tables,
    run_bounds_all,
    run_csr_assemble,
    run_remainders,
    run_remainders_np,
)

_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:  # pre-0.4.38 releases: not yet graduated
    from jax.experimental.shard_map import shard_map as _shard_map


def split_at_run_boundaries(keys: np.ndarray, n_shards: int) -> list[int]:
    """Split points for sorted ``keys`` into ``n_shards`` near-equal
    chunks, snapped left to run starts so equal keys never straddle a
    boundary. Returns n_shards+1 offsets."""
    n = len(keys)
    splits = [0]
    for i in range(1, n_shards):
        ideal = (n * i) // n_shards
        if ideal <= splits[-1]:
            splits.append(splits[-1])
            continue
        snapped = int(np.searchsorted(keys, keys[ideal], side="left"))
        splits.append(max(snapped, splits[-1]))
    splits.append(n)
    return splits


class ShardedTpuSpatialBackend(TpuSpatialBackend):
    """Multi-chip backend: same host authority and observable semantics
    as the single-chip backend, base segment sharded over ``mesh``."""

    def __init__(
        self, cube_size: int, mesh: Mesh,
        compact_threshold: int | None = None,
    ):
        super().__init__(cube_size, compact_threshold=compact_threshold)
        if set(mesh.axis_names) != {"batch", "space"}:
            raise ValueError("mesh must have axes ('batch', 'space')")
        self.mesh = mesh
        self.n_batch = mesh.shape["batch"]
        self.n_space = mesh.shape["space"]
        self._kernels: dict[tuple, object] = {}

    def supports_delta_ticks(self) -> bool:
        """Result reuse runs on the mesh via PER-SHARD FLAT-REGION
        replay (ISSUE 14 satellite, the PR 13 leftover): the reuse
        cache and its validity tracking are HOST state shared with the
        single-chip backend (signatures, per-cube dirty sequence,
        `_install_base` floors — all fed by the same mutation paths
        this class inherits), so a clean query replays its cached
        fan-out without touching any device; only the dirty partition
        dispatches, through the ordinary mesh kernels, whose CSR
        results are assembled as per-batch-shard flat regions and
        decoded by this class's own region-walk overrides — the pmax
        merge happens (or is skipped) per sub-batch exactly as it
        would for a full tick. Replay correctness therefore never
        depends on the mesh layout; layout only shapes what the dirty
        partition computes. Pinned lane-for-lane against the
        full-recompute mesh by the randomized-churn parity suite."""
        return True

    def _delta_scatter_supported(self) -> bool:
        # the O(K) tombstone scatter targets the single-device sorted
        # DELTA segment; the mesh replicates that segment, so delta
        # sync keeps the full-sort path (orthogonal to result reuse —
        # reuse replays results, the scatter maintains the hash)
        return False

    # region: shardings

    def _sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def _base_specs(self):
        # (key, key2, peer, run-remainder, tbl, oflow) — 1-D columns
        # and the [B, 2E] packed probe table as per-shard stacks
        v = P("space", None)
        t = P("space", None, None)
        return (v, v, v, v, t, v)

    def _delta_specs(self):
        v = P(None)
        t = P(None, None)
        return (v, v, v, v, t, v)

    def _query_specs(self):
        # (key, key2, sender, repl)
        return (P("batch"), P("batch"), P("batch"), P("batch"))

    # endregion

    # region: device upload seams

    def _upload_base(self, keys, keys2, pids, k) -> dict:
        splits = split_at_run_boundaries(keys, self.n_space)
        cap = next_pow2(max(b - a for a, b in zip(splits, splits[1:])))

        def stack(arr: np.ndarray, fill) -> np.ndarray:
            return np.stack([
                pad_to(arr[a:b], cap, fill)
                for a, b in zip(splits, splits[1:])
            ])

        # runs never straddle a shard boundary (splits snap to run
        # starts), so each shard's run-remainder column (and its probe
        # table — shard-local run starts) derives from its own padded
        # key rows
        padded_keys = stack(keys, PAD_KEY)
        rems = np.stack([run_remainders_np(row) for row in padded_keys])
        n_cubes = max(
            n_distinct(keys[a:b]) for a, b in zip(splits, splits[1:])
        )
        sub = self._sharding("space", None)
        sk = jax.device_put(padded_keys, sub)
        sk2 = jax.device_put(stack(keys2, np.int64(0)), sub)
        rem = jax.device_put(rems, sub)
        tbl, oflow = self._probe_stack(sk, sk2, probe_buckets_for(n_cubes))
        return {
            "dev": (
                sk,
                sk2,
                jax.device_put(stack(pids.astype(np.int32), np.int32(-1)),
                               sub),
                rem, tbl, oflow,
            ),
            "cap": self.n_space * cap,
            "splits": np.asarray(splits, np.int64),
            "shard_cap": cap,
        }

    def _probe_stack(self, sk_stack, sk2_stack, n_buckets: int):
        """Per-shard probe tables for a [n_space, cap] base stack —
        vmapped over the shard dim with matching shardings, so each
        device builds the table for its own rows locally."""
        key = ("probe_stack", n_buckets)
        kernel = self._kernels.get(key)
        if kernel is None:
            kernel = self._kernels[key] = jax.jit(
                jax.vmap(
                    lambda sk, sk2: probe_tables(
                        sk, sk2, n_buckets=n_buckets
                    )
                ),
                in_shardings=(
                    self._sharding("space", None),
                    self._sharding("space", None),
                ),
                out_shardings=(
                    self._sharding("space", None, None),
                    self._sharding("space", None),
                ),
            )
            retrace.GUARD.register("sharded.probe_stack", kernel)
        return kernel(sk_stack, sk2_stack)

    #: re-shard (full re-upload) only when the largest shard exceeds
    #: this multiple of the mean — keys are uniform hashes, so the old
    #: key-range boundaries stay balanced as the index grows and the
    #: bound ~never trips in practice
    RESHARD_IMBALANCE = 2.0

    def _compact_device(
        self, snap: dict, cap2: int, host_arrays, k, n_buckets: int
    ) -> dict:
        """Mesh-aware compaction in O(churn) transfer: every shard owns
        a contiguous KEY range (the old split boundaries), the delta is
        already device-resident and replicated, so each shard folds
        (its base rows ∪ the delta rows hashing into its range) locally
        — one vmapped on-device sort per shard, H2D limited to the
        [n_space] boundary-key vector. The old global sort restricted
        to a key range IS the new shard content, so the host mirror
        (folded by ``_compact_work``'s identical stable transform) and
        the device stacks stay row-aligned via the re-derived splits.

        Falls back to a full host re-upload (fresh balanced splits)
        when there is no resident base/boundary state yet or when
        shard sizes drift past RESHARD_IMBALANCE — deferred
        re-sharding: uniform key hashes keep old boundaries balanced,
        so the common compaction ships ~nothing over the link. Runs on
        the compaction worker thread, so neither path touches the
        owning event loop."""
        hk, hk2, hp = host_arrays
        base = snap.get("base_bundle")
        if (
            base is None
            or base.get("splits") is None
            or self.n_space == 1
            or snap.get("delta_buf") is None
        ):
            return self._upload_base(hk, hk2, hp, k)

        old_splits = base["splits"]
        old_bk = snap["bk"]
        # boundary KEYS from the old row splits (first key of each
        # shard after the first); the new fold re-derives row splits
        # from the same keys, so runs still never straddle shards
        bounds = np.empty(self.n_space + 1, np.int64)
        bounds[0] = np.iinfo(np.int64).min
        bounds[-1] = np.int64(PAD_KEY)  # live keys are always < PAD_KEY
        for s in range(1, self.n_space):
            row = int(old_splits[s])
            bounds[s] = old_bk[row] if row < old_bk.size else PAD_KEY

        new_splits = np.empty(self.n_space + 1, np.int64)
        new_splits[0] = 0
        new_splits[-1] = hk.size
        new_splits[1:-1] = np.searchsorted(
            hk, bounds[1:-1], side="left"
        )
        # live rows per shard: dead/pad rows were rewritten to PAD_KEY
        # before the host sort, so the live prefix ends at the first
        # PAD row and the pad tail must not skew balance accounting
        live_n = int(np.searchsorted(hk, PAD_KEY, side="left"))
        edges = np.minimum(new_splits, live_n)
        counts = np.diff(edges)
        mean = max(live_n / self.n_space, 1.0)
        if counts.max() > self.RESHARD_IMBALANCE * mean + 8:
            return self._upload_base(hk, hk2, hp, k)

        cap_shard = next_pow2(int(counts.max()))
        # probe tables sized for the busiest SHARD's cube count, not
        # the global one (global would allocate S× the needed rows)
        n_cubes = 1
        for s in range(self.n_space):
            a, b = int(edges[s]), int(edges[s + 1])
            if b > a:
                n_cubes = max(n_cubes, n_distinct(hk[a:b]))
        n_buckets = probe_buckets_for(n_cubes)
        bk, bk2, bp = base["dev"][:3]
        if cap_shard > bk.shape[1] + snap["delta_buf"][0].shape[0]:
            # cannot happen (new rows <= old rows + delta rows), but a
            # silent wrong-shape fold would corrupt the index — guard
            return self._upload_base(hk, hk2, hp, k)
        dev = self._fold_shards(
            bk, bk2, bp, snap["delta_buf"],
            jnp.asarray(bounds[:-1]), jnp.asarray(bounds[1:]),
            cap_shard, n_buckets,
        )
        return {
            "dev": dev,
            "cap": self.n_space * cap_shard,
            "splits": new_splits,
            "shard_cap": cap_shard,
        }

    def _fold_shards(self, bk, bk2, bp, delta, lo, hi, cap2: int,
                     n_buckets: int):
        """vmapped per-shard fold: local base rows + the delta rows in
        [lo, hi) → fresh sorted shard with run-remainders and probe
        tables. Tombstones and out-of-range delta rows sink past the
        live rows as PAD_KEY (their peers are <0 or their keys padded,
        so no consumer can see them)."""
        key = ("fold_shards", cap2, n_buckets, bk.shape, delta[0].shape)
        kernel = self._kernels.get(key)
        if kernel is None:
            def fold_one(bk, bk2, bp, lo, hi, dk, dk2, dp):
                in_range = (dk >= lo) & (dk < hi) & (dp >= 0)
                dkm = jnp.where(in_range, dk, PAD_KEY)
                dpm = jnp.where(in_range, dp, -1)
                keys = jnp.concatenate(
                    [jnp.where(bp < 0, PAD_KEY, bk), dkm]
                )
                keys2 = jnp.concatenate([bk2, dk2])
                peers = jnp.concatenate([bp, dpm])
                order = jnp.argsort(keys, stable=True)[:cap2]
                sk = keys[order]
                sk2 = keys2[order]
                rem = run_remainders(sk)
                tbl_a, oflow = probe_tables(sk, sk2, n_buckets=n_buckets)
                return (sk, sk2, peers[order], rem, tbl_a, oflow)

            sub = self._sharding("space", None)
            vec = self._sharding("space")
            rep = self._sharding(None)
            tbl = self._sharding("space", None, None)
            kernel = self._kernels[key] = jax.jit(
                jax.vmap(
                    fold_one,
                    in_axes=(0, 0, 0, 0, 0, None, None, None),
                ),
                in_shardings=(sub, sub, sub, vec, vec, rep, rep, rep),
                out_shardings=(sub, sub, sub, sub, tbl, vec),
            )
            retrace.GUARD.register("sharded.fold_shards", kernel)
        return kernel(bk, bk2, bp, lo, hi, *delta)

    # -- delta seams: the delta segment is replicated across the mesh,
    # so allocate/write/sort with explicit replicated out_shardings —
    # otherwise the buffers commit to device 0 and every dispatch
    # re-transfers them to the other shards. --

    def _rep_kernel(self, name: str, fn, static=(), spec=()):
        kernel = self._kernels.get(name)
        if kernel is None:
            kernel = self._kernels[name] = jax.jit(
                fn, static_argnames=static,
                out_shardings=self._sharding(*spec),
            )
            retrace.GUARD.register(f"sharded.{name}", kernel)
        return kernel

    def _alloc_delta_buffer(self, cap: int) -> tuple:
        return self._rep_kernel("alloc_delta", _alloc_buffers, ("cap",))(
            cap=cap
        )

    def _grow_delta_buffer(self, bufs: tuple, cap: int) -> tuple:
        return self._rep_kernel("grow_delta", _grow_buffers, ("cap",))(
            bufs, cap=cap
        )

    def _write_delta_chunk(self, bufs: tuple, chunk: tuple, start: int):
        return self._rep_kernel("write_delta", _write_chunk)(
            bufs, chunk, np.int32(start)
        )

    def _scatter_delta_dead(self, peer_buf, rows: np.ndarray):
        return self._rep_kernel("scatter_delta", _scatter_dead)(
            peer_buf, rows
        )

    def _sort_delta(self, bufs: tuple, n_buckets: int) -> tuple:
        key = ("sort_delta", n_buckets)
        kernel = self._kernels.get(key)
        if kernel is None:
            v, t = self._sharding(None), self._sharding(None, None)
            kernel = self._kernels[key] = jax.jit(
                _sort_segment_dev, static_argnames=("n_buckets",),
                out_shardings=(v, v, v, v, t, v),
            )
            retrace.GUARD.register("sharded.sort_delta", kernel)
        return kernel(*bufs, n_buckets=n_buckets)

    def _scatter_base_dead(self, bundle: dict, rows: np.ndarray) -> dict:
        """Map global sorted-row indices → (shard, local) and tombstone
        with one scatter over the [n_space, cap] peer array."""
        splits = bundle["splits"]
        cap = bundle["shard_cap"]
        shard = np.searchsorted(splits, rows, side="right") - 1
        local = rows - splits[shard]
        pad_n = next_pow2(rows.size)
        shard = pad_to(shard.astype(np.int32), pad_n, np.int32(self.n_space))
        local = pad_to(local.astype(np.int32), pad_n, np.int32(cap))
        dev = bundle["dev"]
        kernel = self._rep_kernel(
            "scatter",
            lambda peer, s, l: peer.at[s, l].set(-1, mode="drop"),
            spec=("space", None),
        )
        return {
            **bundle,
            "dev": (*dev[:2], kernel(dev[2], shard, local), *dev[3:]),
        }

    # endregion

    # region: dispatch

    def _query_cap(self, m: int) -> int:
        # Batch capacity must shard evenly over 'batch': power-of-two
        # tier, rounded up to a multiple of n_batch (which need not be
        # a power of two).
        cap = max(next_pow2(m), self.n_batch)
        return -(-cap // self.n_batch) * self.n_batch

    def _make_kernel(self, variant: str, kinds: tuple, ks: tuple, extra):
        """Compile a mesh kernel: shard_map match (+ pmax merge), then
        optional result compaction, one jit, explicit in_shardings.
        ``kinds`` says which segments are space-sharded stacks ('base',
        local view [1, cap]) vs replicated flat arrays ('delta')."""
        mesh = self.mesh
        n_seg = len(kinds)

        na = SEG_ARRAYS

        def local_segs(args):
            for i, kind in enumerate(kinds):
                seg = args[na * i:na * i + na]
                if kind == "base":
                    seg = tuple(a[0] for a in seg)  # drop the shard dim
                yield seg

        def local(*args):
            queries = args[na * n_seg:]
            parts = [
                match_core(seg, *queries, k=k)
                for seg, k in zip(local_segs(args), ks)
            ]
            tgt = parts[0] if n_seg == 1 else jnp.concatenate(parts, axis=1)
            # Exactly one 'space' shard holds any cube's base run, and
            # the delta part is identical on every shard — max is a
            # lossless merge either way.
            return jax.lax.pmax(tgt, "space")

        in_specs = tuple(
            spec
            for kind in kinds
            for spec in (
                self._base_specs() if kind == "base" else self._delta_specs()
            )
        ) + self._query_specs()

        if variant == "csr":
            # per-batch-shard result budget: each shard assembles its
            # own flat region; the host walks them shard by shard
            t_cap_local = extra // self.n_batch

            def local_csr(*args):
                segs = list(local_segs(args))
                queries = args[na * n_seg:]
                los, cnts_local = run_bounds_all(segs, queries)
                # a run lives on exactly one space shard — the global
                # raw counts (and therefore the layout every shard
                # agrees on) are the pmax union
                cnts = [
                    jax.lax.pmax(c, "space") for c in cnts_local
                ]
                counts, flat, total = run_csr_assemble(
                    segs, los, cnts, cnts_local, queries, t_cap_local
                )
                # owner shards wrote real lanes, the rest -1: max is a
                # lossless merge (same argument as the dense path)
                flat = jax.lax.pmax(flat, "space")
                total = jax.lax.pmax(total, "space")
                return counts, flat, total.reshape(1)

            matched_csr = _shard_map(
                local_csr, mesh=mesh, in_specs=in_specs,
                out_specs=(
                    P("batch", None), P("batch"), P("batch"),
                ),
            )

            def fn(*args):
                counts, flat, totals = matched_csr(*args)
                # any shard overflowing its local budget triggers the
                # global retry sentinel
                total = jnp.where(
                    (totals > t_cap_local).any(),
                    jnp.int32(extra + 1),
                    totals.sum(dtype=jnp.int32),
                )
                return counts, flat, total
        else:
            matched = _shard_map(
                local, mesh=mesh, in_specs=in_specs,
                out_specs=P("batch", None),
            )
            if variant == "dense":
                fn = matched
            else:
                def fn(*args):
                    return compact_sparse(matched(*args), c=extra)

        in_shardings = tuple(
            NamedSharding(mesh, spec) for spec in in_specs
        )
        return jax.jit(fn, in_shardings=in_shardings)

    def _kernel(self, variant: str, kinds, ks, extra=None):
        key = (variant, kinds, ks, extra)
        kernel = self._kernels.get(key)
        if kernel is None:
            kernel = self._kernels[key] = self._make_kernel(
                variant, kinds, ks, extra
            )
            retrace.GUARD.register(f"sharded.match_{variant}", kernel)
        return kernel

    def _dispatch(self, queries: tuple, segs, ks, kinds):
        flat = [a for seg in segs for a in seg]
        return self._kernel("dense", kinds, ks)(*flat, *queries)

    def _dispatch_sparse(self, queries: tuple, segs, ks, kinds, c: int):
        flat = [a for seg in segs for a in seg]
        return self._kernel("sparse", kinds, ks, c)(*flat, *queries)

    def _dispatch_csr(self, queries: tuple, segs, ks, kinds, t_cap: int):
        flat = [a for seg in segs for a in seg]
        return self._kernel("csr", kinds, ks, t_cap)(*flat, *queries)

    def _csr_effective_cap(self, t_cap: int, queries: tuple, segs) -> int:
        # every batch shard's local region must cover its own zone-A
        # identity rows PLUS at least one zone-B row — the base
        # class's global floor divided by n_batch can land exactly on
        # the zone-A size for small multi-segment ticks. Raised HERE
        # (not silently inside the dispatch) so dispatch_local_batch
        # records the same cap the kernel's overflow sentinel uses
        # (ADVICE r5: totals between the two caps used to take a
        # spurious dense re-resolve).
        m_local = queries[0].shape[0] // self.n_batch
        need_local = (CSR_ROW * m_local * len(segs)
                      + 2 * CSR_ROW_B)
        return max(t_cap, next_pow2(self.n_batch * need_local))

    def _pack_kernel(self, bucket_local: int, mq: int, nseg: int,
                     flat_len: int):
        """Per-batch-shard pack_csr, vmapped over the shard dim with
        batch shardings so every shard compacts its own flat region
        locally — no cross-device traffic, the merge already happened
        in the CSR kernel's pmax."""
        key = ("pack_csr", bucket_local, mq, nseg, flat_len)
        kernel = self._kernels.get(key)
        if kernel is None:
            nb = self.n_batch

            def pack_all(counts, flat):
                c3 = counts.reshape(nb, mq // nb, nseg)
                f2 = flat.reshape(nb, flat_len // nb)
                packed, totals = jax.vmap(
                    lambda c, f: pack_csr(c, f, bucket=bucket_local)
                )(c3, f2)
                return packed.reshape(-1), totals

            kernel = self._kernels[key] = jax.jit(
                pack_all,
                in_shardings=(
                    self._sharding("batch", None),
                    self._sharding("batch"),
                ),
                out_shardings=(
                    self._sharding("batch"), self._sharding("batch"),
                ),
            )
            retrace.GUARD.register("sharded.pack_csr", kernel)
        return kernel

    def _compact_fetch(self, counts, flat, total: int, t_cap: int):
        """Mesh compaction: each batch shard packs its own flat region
        into a local bucket sized for 2x imbalance headroom over a
        perfectly balanced split. Shards report their raw totals; any
        shard overflowing its bucket (imbalance past the headroom)
        falls back to the full fetch — slower, never wrong."""
        nb = self.n_batch
        bucket_local = next_pow2(
            max(-(-2 * total // nb), self.compact_min_bucket)
        )
        if (
            not self._compact_applicable(t_cap)
            or bucket_local * nb * 2 > t_cap
        ):
            return None
        mq, nseg = counts.shape
        kernel = self._pack_kernel(
            bucket_local, mq, nseg, flat.shape[0]
        )
        packed, totals = kernel(counts, flat)
        # fit check first — a tiny [n_batch] fetch, not the payload
        totals_np = np.asarray(totals)  # wql: allow(jax-host-sync) — [n_batch] scalars
        if totals_np.size and int(totals_np.max()) > bucket_local:
            return None
        out = np.asarray(packed)  # wql: allow(jax-host-sync) — compacted collect point
        self._note_fetch(bucket_local * nb, bucket_local * nb)
        return out

    def _decode_packed(self, counts, packed, m: int):
        """The mesh packed result is per-batch-shard buckets
        concatenated; walk each shard's queries against its own
        bucket (mirrors the zoned-layout region walk below)."""
        nb = self.n_batch
        bucket_local = len(packed) // nb
        m_local = counts.shape[0] // nb
        out: list = []
        for b in range(nb):
            if len(out) >= m:
                break
            out.extend(super()._decode_packed(
                counts[b * m_local:(b + 1) * m_local],
                packed[b * bucket_local:(b + 1) * bucket_local],
                min(m_local, m - len(out)),
            ))
        return out

    def _decode_csr(self, counts, flat, m: int):
        """The mesh flat result is per-batch-shard regions of
        ``t_cap // n_batch`` slots concatenated; walk each shard's
        queries against its own region. The dense-fallback layout
        (counts.ndim == 1) is host-built and global — no regions."""
        if counts.ndim == 1:
            return super()._decode_csr(counts, flat, m)
        nb = self.n_batch
        t_cap_local = len(flat) // nb
        m_local = counts.shape[0] // nb
        out: list = []
        for b in range(nb):
            if len(out) >= m:
                break
            sub = super()._decode_csr(
                counts[b * m_local:(b + 1) * m_local],
                flat[b * t_cap_local:(b + 1) * t_cap_local],
                min(m_local, m - len(out)),
            )
            out.extend(sub)
        return out

    # endregion

    def device_stats(self) -> dict:
        stats = super().device_stats()
        stats["mesh"] = {"batch": self.n_batch, "space": self.n_space}
        return stats
