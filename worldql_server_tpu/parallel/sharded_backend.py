"""Spatial fan-out sharded over a device mesh.

Scale-out design (BASELINE configs 4-5): the sorted subscription index
is split into per-device contiguous key ranges — split points snapped
to cube-run boundaries so every cube's subscriber run lives wholly on
one device. Queries shard over the ``batch`` axis. Each device binary-
searches its local range; exactly one ``space`` shard can match a given
cube, so partial [M/b, K] results (−1 = no match) combine with a single
``pmax`` over ``space`` — one ICI collective per tick, no host hops.

SPMD via ``jax.shard_map``; XLA lays out the gathers per shard and the
final combine as an ICI all-reduce(max). Worlds need no special
handling: world id is part of the spatial key, so a world's cubes
scatter across shards (load-balancing Zipf-hotspot worlds) while each
cube stays device-local.
"""

from __future__ import annotations

import numpy as np

from ..spatial import jaxconf  # noqa: F401  (must precede jax import)
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..spatial.hashing import NO_WORLD, PAD_KEY, next_pow2, pad_to
from ..spatial.tpu_backend import TpuSpatialBackend, match_core


def split_at_run_boundaries(keys: np.ndarray, n_shards: int) -> list[int]:
    """Split points for sorted ``keys`` into ``n_shards`` near-equal
    chunks, snapped left to run starts so equal keys never straddle a
    boundary. Returns n_shards+1 offsets."""
    n = len(keys)
    splits = [0]
    for i in range(1, n_shards):
        ideal = (n * i) // n_shards
        if ideal <= splits[-1]:
            splits.append(splits[-1])
            continue
        snapped = int(np.searchsorted(keys, keys[ideal], side="left"))
        splits.append(max(snapped, splits[-1]))
    splits.append(n)
    return splits


def _sharded_match(mesh: Mesh, k: int):
    """Build the jitted shard_map kernel for this mesh and fan-out K."""

    def local(sub_key, sub_world, sub_xyz, sub_peer,
              q_key, q_world, q_xyz, q_sender, q_repl):
        tgt = match_core(
            sub_key[0], sub_world[0], sub_xyz[0], sub_peer[0],
            q_key, q_world, q_xyz, q_sender, q_repl, k=k,
        )
        # Exactly one 'space' shard holds any cube's run; everyone else
        # contributes -1, so max is a lossless merge.
        return jax.lax.pmax(tgt, "space")

    sub = P("space", None)
    return jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(
                sub, sub, P("space", None, None), sub,
                P("batch"), P("batch"), P("batch", None),
                P("batch"), P("batch"),
            ),
            out_specs=P("batch", None),
        )
    )


class ShardedTpuSpatialBackend(TpuSpatialBackend):
    """Multi-chip backend: same host authority and observable semantics
    as the single-chip backend, index sharded over ``mesh``."""

    def __init__(self, cube_size: int, mesh: Mesh):
        super().__init__(cube_size)
        if set(mesh.axis_names) != {"batch", "space"}:
            raise ValueError("mesh must have axes ('batch', 'space')")
        self.mesh = mesh
        self.n_batch = mesh.shape["batch"]
        self.n_space = mesh.shape["space"]
        self._kernels: dict[int, object] = {}  # k → compiled shard_map

    # region: device mirror (sharded)

    def flush(self) -> None:
        if not self._dirty:
            return
        self._dirty = False

        built = self._build_sorted()
        if built is None:
            self._dev = None
            return
        keys, worlds, xyz, peers, cube_occupancy = built
        self._k = next_pow2(cube_occupancy, 8)

        splits = split_at_run_boundaries(keys, self.n_space)
        cap = next_pow2(max(b - a for a, b in zip(splits, splits[1:])))

        def stack(arr: np.ndarray, fill) -> np.ndarray:
            return np.stack([
                pad_to(arr[a:b], cap, fill)
                for a, b in zip(splits, splits[1:])
            ])

        def put(arr: np.ndarray, spec: P):
            return jax.device_put(arr, NamedSharding(self.mesh, spec))

        sub = P("space", None)
        self._dev = (
            put(stack(keys, PAD_KEY), sub),
            put(stack(worlds, NO_WORLD), sub),
            put(stack(xyz, np.int64(-(2**62))), P("space", None, None)),
            put(stack(peers, np.int32(-1)), sub),
        )

    # endregion

    # region: batched hot path

    def _query_cap(self, m: int) -> int:
        # Batch capacity must shard evenly over 'batch': power-of-two
        # tier, rounded up to a multiple of n_batch (which need not be
        # a power of two).
        cap = max(next_pow2(m), self.n_batch)
        return -(-cap // self.n_batch) * self.n_batch

    def _dispatch_sparse(self, queries: tuple, c: int):
        raise NotImplementedError(
            "sparse/CSR compaction over a sharded mesh lands with the "
            "distributed delivery path; use the dense API here"
        )

    def _dispatch_csr(self, queries: tuple, t_cap: int):
        raise NotImplementedError(
            "sparse/CSR compaction over a sharded mesh lands with the "
            "distributed delivery path; use the dense API here"
        )

    def _dispatch(self, queries: tuple):
        kernel = self._kernels.get(self._k)
        if kernel is None:
            kernel = self._kernels[self._k] = _sharded_match(self.mesh, self._k)

        keys, world_ids, cubes, sender_ids, repls = queries

        def put(arr, *spec):
            return jax.device_put(arr, NamedSharding(self.mesh, P(*spec)))

        return kernel(
            *self._dev,
            put(keys, "batch"),
            put(world_ids, "batch"),
            put(cubes, "batch", None),
            put(sender_ids, "batch"),
            put(repls, "batch"),
        )

    # endregion

    def device_stats(self) -> dict:
        stats = super().device_stats()
        stats["mesh"] = {"batch": self.n_batch, "space": self.n_space}
        if self._dev is not None:
            stats["capacity"] = int(
                self._dev[0].shape[0] * self._dev[0].shape[1]
            )
        return stats
