"""Device-mesh construction for the fan-out engine.

Axes:

* ``batch`` — data parallelism over the per-tick query batch; each
  device resolves M/b queries.
* ``space`` — the spatial index sharded by contiguous sorted-key
  ranges; the domain's analog of sequence/context parallelism
  (SURVEY §5: "sharding space, not sequence").

On a real slice the mesh should be built so ``space`` rides ICI
(neighbor collectives dominate); ``batch`` only ever combines at the
end of a tick.
"""

from __future__ import annotations

from ..spatial import jaxconf  # noqa: F401  (must precede jax import)
import jax
from jax.sharding import Mesh


def make_fanout_mesh(
    n_batch: int = 1, n_space: int | None = None, devices=None
) -> Mesh:
    """Build a ('batch', 'space') mesh. With only ``n_batch`` given,
    ``space`` takes all remaining devices."""
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if n_space is None:
        if n % n_batch:
            raise ValueError(f"{n} devices not divisible by batch={n_batch}")
        n_space = n // n_batch
    if n_batch * n_space > n:
        raise ValueError(
            f"mesh {n_batch}x{n_space} exceeds {n} available devices"
        )
    import numpy as np

    grid = np.array(devices[: n_batch * n_space]).reshape(n_batch, n_space)
    return Mesh(grid, axis_names=("batch", "space"))
