"""Device-mesh construction for the fan-out engine.

Axes:

* ``batch`` — data parallelism over the per-tick query batch; each
  device resolves M/b queries.
* ``space`` — the spatial index sharded by contiguous sorted-key
  ranges; the domain's analog of sequence/context parallelism
  (SURVEY §5: "sharding space, not sequence").

On a real slice the mesh should be built so ``space`` rides ICI
(neighbor collectives dominate); ``batch`` only ever combines at the
end of a tick.

**Multi-host (DCN):** where the reference would scale out with a
second process and NCCL/MPI-style plumbing, a JAX multi-host run is
one ``jax.distributed.initialize`` per process and the SAME mesh code:
``jax.devices()`` then spans every host's chips and the sharded
backend's collectives ride ICI within a host and DCN across hosts with
no further changes. :func:`maybe_initialize_distributed` wires that
from ``WQL_DIST_*`` environment variables so every process of a
multi-host deployment runs the identical server command.
"""

from __future__ import annotations

import logging
import os

from ..spatial import jaxconf  # noqa: F401  (must precede jax import)
import jax
from jax.sharding import Mesh

logger = logging.getLogger(__name__)


def maybe_initialize_distributed() -> bool:
    """Join a multi-host JAX runtime if ``WQL_DIST_COORDINATOR`` is
    set (``host:port`` of process 0), using ``WQL_DIST_NUM_PROCESSES``
    and ``WQL_DIST_PROCESS_ID``. No-op (returns False) when unset —
    single-host runs need nothing. Must run before the first device
    query, which is why build_backend calls it ahead of mesh
    construction."""
    coordinator = os.environ.get("WQL_DIST_COORDINATOR")
    if not coordinator:
        return False
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return True  # second build_backend in one process: no-op
    try:
        num = int(os.environ["WQL_DIST_NUM_PROCESSES"])
        pid = int(os.environ["WQL_DIST_PROCESS_ID"])
    except KeyError as exc:
        raise ValueError(
            "WQL_DIST_COORDINATOR is set but "
            f"{exc.args[0]} is not — a partial multi-host config "
            "would silently run single-host"
        ) from None
    except ValueError as exc:
        raise ValueError(
            "WQL_DIST_NUM_PROCESSES / WQL_DIST_PROCESS_ID must be "
            f"integers: {exc}"
        ) from None
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num,
        process_id=pid,
    )
    logger.info(
        "joined distributed runtime: process %d/%d via %s "
        "(%d global devices)",
        pid, num, coordinator, jax.device_count(),
    )
    return True


def make_fanout_mesh(
    n_batch: int = 1, n_space: int | None = None, devices=None
) -> Mesh:
    """Build a ('batch', 'space') mesh. With only ``n_batch`` given,
    ``space`` takes all remaining devices."""
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if n_space is None:
        if n % n_batch:
            raise ValueError(f"{n} devices not divisible by batch={n_batch}")
        n_space = n // n_batch
    if n_batch * n_space > n:
        raise ValueError(
            f"mesh {n_batch}x{n_space} exceeds {n} available devices"
        )
    import numpy as np

    grid = np.array(devices[: n_batch * n_space]).reshape(n_batch, n_space)
    return Mesh(grid, axis_names=("batch", "space"))
