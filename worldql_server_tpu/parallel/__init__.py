"""Multi-chip scale-out: mesh construction and the sharded spatial
backend (SURVEY §7 step 6; BASELINE configs 4-5).

The reference scales by running more tokio tasks in one process
(SURVEY §2 "Parallelism") — there is no multi-node story. Here the
scale axis is a ``jax.sharding.Mesh``: subscriptions shard across the
``space`` axis (the domain's sequence/context parallelism — sharding
space, not sequence), query batches across the ``batch`` axis (data
parallelism), and per-query partial results combine with one ``pmax``
collective over ICI.
"""

from .mesh import make_fanout_mesh, maybe_initialize_distributed
from .sharded_backend import ShardedTpuSpatialBackend

__all__ = [
    "make_fanout_mesh",
    "maybe_initialize_distributed",
    "ShardedTpuSpatialBackend",
]
