"""The fully-on-device simulation tick — flagship compute path.

One jitted step over SoA entity arrays does everything the reference's
per-message hot loop does (SURVEY §3.2), but for EVERY entity at once:

1. integrate positions (reflecting off the world bounds),
2. re-quantize every entity to its subscription cube,
3. rebuild the spatial hash for the tick (one device sort — the
   "per-tick spatial-hash rebuild" of BASELINE config 5),
4. resolve every entity's broadcast as a stencil over the sort:
   co-cube members are sort-order neighbors, so the ±(K-1) candidate
   window is a pad + stack-of-slices with same-run masks — no random
   gather (a [N, K] element gather costs ~8 ns/element on TPU and
   dominated this tick; the slice stack fuses into one kernel),
5. order each entity's neighbors nearest-first (batched kNN: top-k by
   squared distance over the stencil window).

Static shapes throughout: N entities and degree K are compile-time;
XLA fuses steps 1-2 and the stencil's roll/mask chains. The sort
(step 3) is the asymptotic cost, O(N log N) on-device, no host
round-trips.

Quantization note: this sim path quantizes in f32 on device
(``device_coord_clamp``), semantically mirroring the golden host
quantizer (spatial/quantize.py, cube_area.rs:23-44). The agreement
envelope is PINNED by tests/test_quantizer_envelope.py: exact for all
normal finite inputs when the cube size is a power of two (every f32
step is an exponent shift; tested to |x| <= 2^62), and exact for
|x| <= size * 2^21 for non-power-of-two sizes (the f32 quotient loses
sub-integer resolution near |x|/size ~ 2^24 and diverges heavily past
size * 2^26); f32 subnormals (|x| < 2^-126) are outside the envelope.
Specials match the host exactly (NaN → +size, ±inf → ±i64::MAX,
saturating arithmetic). The authoritative broker path
(spatial/tpu_backend.py) always quantizes host-side in f64; this module
serves the embedded-simulation / benchmark workloads where positions
are device-resident. Hash collisions between distinct cubes merge
their neighbor lists; at ~2⁻⁶⁴ per cube pair this is below sim noise.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

from ..spatial import jaxconf  # noqa: F401  (must precede jax import)
import jax
import jax.numpy as jnp

from ..spatial.hashing import MIX_GOLDEN, MIX_M1, MIX_M2


class EntityState(NamedTuple):
    """SoA device state for one entity population."""

    position: jax.Array  # [N, 3] f32
    velocity: jax.Array  # [N, 3] f32
    world: jax.Array     # [N] i32 interned world id
    peer: jax.Array      # [N] i32 dense peer id


def device_coord_clamp(x: jax.Array, size: int) -> jax.Array:
    """Subscription-cube quantizer on device (f32 → i64 labels).

    Mirrors the max-corner / sign-symmetric / 0→+size semantics of the
    golden host quantizer (cube_area.rs:23-44).
    """
    size_f = jnp.float32(size)
    i64_max = jnp.int64(2**63 - 1)
    a = jnp.abs(x)
    mult = jnp.where(x < 0, -1, 1).astype(jnp.int64)
    rounded = jnp.ceil(a / size_f) * size_f
    rounded = jnp.where(a == 0.0, size_f, rounded)
    exact = (jnp.mod(a, size_f) == 0.0) & (x != 0.0)
    ri = rounded.astype(jnp.int64)
    # saturating +size like the host (_sat_add): past the int64 cast's
    # saturation point a plain add wraps negative
    bumped = jnp.where(ri > i64_max - size, i64_max, ri + size)
    res = jnp.where(rounded > a, ri, bumped)
    res = jnp.where(exact, a.astype(jnp.int64), res)
    # NaN → +size, ±inf → ±i64::MAX, and saturation-zone finites →
    # ±i64::MAX like the host quantizer's Rust-style saturating casts
    # (XLA's out-of-range float→int casts are platform-defined, so
    # every cast is guarded explicitly). The guard tests ROUNDED — the
    # actual cast input — not `a`: f32 round-up can push `rounded` to
    # exactly 2^63 while `a` is still below it, and rounded >= a always
    # holds, so this also covers the exact-branch cast of `a`.
    res = jnp.where(rounded >= jnp.float32(2.0**63), i64_max, res)
    res = jnp.where(jnp.isinf(x), i64_max, res)
    return jnp.where(jnp.isnan(x), jnp.int64(size), res * mult)


_M1 = jnp.uint64(MIX_M1)
_M2 = jnp.uint64(MIX_M2)
_GOLDEN = jnp.uint64(MIX_GOLDEN)


def _mix(x: jax.Array) -> jax.Array:
    x = (x ^ (x >> jnp.uint64(30))) * _M1
    x = (x ^ (x >> jnp.uint64(27))) * _M2
    return x ^ (x >> jnp.uint64(31))


def device_spatial_keys(
    world: jax.Array, cubes: jax.Array, seed: int = 0
) -> jax.Array:
    """Device twin of spatial/hashing.spatial_keys: [N] i32 world ids +
    [N, 3] i64 cubes → [N] i64 sort keys."""
    h = _mix(jnp.uint64(seed) + _GOLDEN)
    h = _mix(h ^ world.astype(jnp.int64).view(jnp.uint64))
    h = _mix(h ^ cubes[..., 0].view(jnp.uint64))
    h = _mix(h ^ cubes[..., 1].view(jnp.uint64))
    h = _mix(h ^ cubes[..., 2].view(jnp.uint64))
    return h.view(jnp.int64)


def simulation_tick(
    state: EntityState,
    *,
    cube_size: int,
    k: int,
    dt: float = 0.05,
    bounds: float = 1000.0,
    seed: int = 0,
    pallas: bool | None = None,
):
    """One tick: integrate → quantize → rebuild hash → resolve fan-out.

    Returns ``(new_state, targets, counts)`` where ``targets`` is
    [N, K] i32 peer ids each entity broadcasts to this tick (-1 = none;
    except-self), and ``counts`` the exact co-cube population including
    self (callers can detect K-overflow as counts > K).
    """
    n = state.position.shape[0]

    # 1. integrate, reflecting at ±bounds.
    pos = state.position + state.velocity * jnp.float32(dt)
    over = pos > bounds
    under = pos < -bounds
    pos = jnp.where(over, 2.0 * bounds - pos, pos)
    pos = jnp.where(under, -2.0 * bounds - pos, pos)
    vel = jnp.where(over | under, -state.velocity, state.velocity)

    # 2. quantize to subscription cubes.
    cubes = device_coord_clamp(pos, cube_size)

    # 3. per-tick spatial-hash rebuild: one sort.
    keys = device_spatial_keys(state.world, cubes, seed)
    order = jnp.argsort(keys)
    sorted_keys = keys[order]
    sorted_peer = state.peer[order]

    # 4. resolve every entity's broadcast set as a STENCIL over the
    # sort: an entity's co-cube members are its neighbors in sorted
    # order, so the ±(K-1) candidate window is a contiguous slice per
    # shift — no [N, K] random gather. The fixed-degree gather this
    # replaces dominated the tick (27 of 36 ms at 100K entities on
    # v5e: TPU element gathers cost ~8 ns/element). Exact counts
    # still come from the run scan (cheap, and callers use them to
    # detect K-overflow).
    p_idx = jnp.arange(n, dtype=jnp.int32)
    boundary = sorted_keys[1:] != sorted_keys[:-1]
    first = jnp.concatenate([jnp.ones((1,), bool), boundary])
    last = jnp.concatenate([boundary, jnp.ones((1,), bool)])
    run_start = jax.lax.cummax(jnp.where(first, p_idx, 0))
    run_end = jax.lax.cummin(
        jnp.where(last, p_idx + 1, jnp.int32(n)), reverse=True
    )
    counts_sorted = run_end - run_start
    counts = jnp.zeros(n, jnp.int32).at[order].set(counts_sorted)
    # inverse permutation: one cheap [N] scatter, so the final [N, K]
    # un-permute is a row GATHER (take axis 0 — the TPU fast path)
    inv = jnp.zeros(n, jnp.int32).at[order].set(p_idx)

    # 5. true k-nearest selection among the stencil candidates: the
    # ±(K-1) window covers EVERY co-cube member whenever the cube's
    # occupancy L <= K (runs are contiguous in sorted order, so the
    # max sort-order distance between members is L-1). Distance
    # bits and target pack into ONE int64 per candidate so the whole
    # reorder is a single row-sort — lax.top_k costs ~5x more on TPU
    # (measured) for the same result. IEEE bits of a non-negative f32
    # are order-preserving; invalid slots carry the all-ones bit
    # pattern (above +inf AND every NaN — NaN positions are supported
    # inputs, they quantize to cube +size), and equal distances
    # tie-break by peer id (deterministic). With occupancy beyond K
    # the candidate set truncates to the 2(K-1) nearest in sort order
    # (callers detect via counts > K); within it the result is the
    # exact k nearest.
    # The window materializes as a pad + stack-of-slices (one fused
    # concat — a python loop of jnp.roll per shift emits ~2K separate
    # kernel launches, ~20x slower, measured). Run identity compares as
    # a cumsum run id (i32 — exact, and cheaper than the i64 keys);
    # padding rows carry run id -1, so window slots past either array
    # end never match and there is no wraparound to dedup. The self
    # column (shift 0) and duplicate-peer candidates fall to the
    # ``peer != own`` mask, matching the reference's ExceptSelf.
    sorted_pos = pos[order]
    rid = jnp.cumsum(first.astype(jnp.int32))

    if pallas is None:
        pallas = jax.devices()[0].platform == "tpu"
    # k=1 rides the k=2 window, truncated to one target: a ±(k-1)
    # stencil at k=1 is empty and would silently return NO neighbors,
    # while ±1 finds the single nearest whenever occupancy <= 2 — the
    # same exactness contract (L <= K, overflow visible via counts)
    # every other k gets.
    kw = max(k, 2)
    if pallas:
        # fused Pallas kernel: the whole stencil + k-nearest select in
        # one launch (ops/knn_pallas.py) — ~7x over the XLA stencil at
        # 100K entities on v5e (launch- and HBM-round-trip-bound)
        from .knn_pallas import knn_select

        tgt_sorted = knn_select(rid, sorted_peer, sorted_pos, k=kw)[:, :k]
        targets = jnp.take(tgt_sorted, inv, axis=0)
        return (EntityState(pos, vel, state.world, state.peer),
                targets, counts)

    w = 2 * kw - 1
    rid_p = jnp.pad(rid, (kw - 1, kw - 1), constant_values=-1)
    peer_p = jnp.pad(sorted_peer, (kw - 1, kw - 1), constant_values=-1)
    pos_p = jnp.pad(sorted_pos, ((kw - 1, kw - 1), (0, 0)))
    rid_w = jnp.stack([rid_p[s:s + n] for s in range(w)], axis=1)
    peer_w = jnp.stack([peer_p[s:s + n] for s in range(w)], axis=1)
    pos_w = jnp.stack([pos_p[s:s + n] for s in range(w)], axis=1)
    same = (rid_w == rid[:, None]) & (peer_w != sorted_peer[:, None])
    d2 = jnp.sum((pos_w - sorted_pos[:, None, :]) ** 2, axis=-1).astype(
        jnp.float32
    )
    d2_bits = jnp.where(
        same, jax.lax.bitcast_convert_type(d2, jnp.uint32),
        jnp.uint32(0xFFFFFFFF),
    )
    packed = (d2_bits.astype(jnp.uint64) << jnp.uint64(32)) | (
        (jnp.where(same, peer_w, -1) + 1).astype(jnp.uint64)
        & jnp.uint64(0xFFFFFFFF)
    )
    packed = jnp.sort(packed, axis=1)[:, :k]   # k nearest per entity
    tgt_sorted = (packed & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32) - 1
    targets = jnp.take(tgt_sorted, inv, axis=0)

    return EntityState(pos, vel, state.world, state.peer), targets, counts


def make_tick_fn(cube_size: int = 16, k: int = 32, dt: float = 0.05,
                 bounds: float = 1000.0, pallas: bool | None = None):
    """Close the static params; returns a jittable ``fn(state)``.

    ``pallas=None`` auto-selects the fused Pallas resolve on TPU and
    the XLA stencil elsewhere; both paths are semantically identical
    (tests pin their equivalence)."""
    return partial(simulation_tick, cube_size=cube_size, k=k, dt=dt,
                   bounds=bounds, pallas=pallas)


def example_state(n: int = 1024, n_worlds: int = 4, seed: int = 7) -> EntityState:
    """Deterministic small entity population for compile checks."""
    kp, kv = jax.random.split(jax.random.PRNGKey(seed))
    return EntityState(
        position=jax.random.uniform(
            kp, (n, 3), jnp.float32, minval=-900.0, maxval=900.0
        ),
        velocity=jax.random.uniform(
            kv, (n, 3), jnp.float32, minval=-40.0, maxval=40.0
        ),
        world=(jnp.arange(n, dtype=jnp.int32) % n_worlds),
        peer=jnp.arange(n, dtype=jnp.int32),
    )
