"""The fully-on-device simulation tick — flagship compute path.

One jitted step over SoA entity arrays does everything the reference's
per-message hot loop does (SURVEY §3.2), but for EVERY entity at once:

1. integrate positions (reflecting off the world bounds),
2. re-quantize every entity to its subscription cube,
3. rebuild the spatial hash for the tick (one device sort — the
   "per-tick spatial-hash rebuild" of BASELINE config 5),
4. resolve every entity's broadcast: the contiguous run of co-cube
   subscribers via a segment scan over the sort, gathered at fixed
   degree K with except-self masking,
5. order each entity's neighbors nearest-first (batched kNN: top-k by
   squared distance over the candidate window).

Static shapes throughout: N entities and degree K are compile-time;
XLA fuses steps 1-2 and 4's mask/gather chains. The sort (step 3) is
the asymptotic cost, O(N log N) on-device, no host round-trips.

Quantization note: this sim path quantizes in f32 on device
(``device_coord_clamp``), semantically mirroring the golden host
quantizer (spatial/quantize.py, cube_area.rs:23-44) but not bit-exact
for coordinates beyond f32 resolution. The authoritative broker path
(spatial/tpu_backend.py) always quantizes host-side in f64; this module
serves the embedded-simulation / benchmark workloads where positions
are device-resident. Hash collisions between distinct cubes merge
their neighbor lists; at ~2⁻⁶⁴ per cube pair this is below sim noise.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

from ..spatial import jaxconf  # noqa: F401  (must precede jax import)
import jax
import jax.numpy as jnp

from ..spatial.hashing import MIX_GOLDEN, MIX_M1, MIX_M2


class EntityState(NamedTuple):
    """SoA device state for one entity population."""

    position: jax.Array  # [N, 3] f32
    velocity: jax.Array  # [N, 3] f32
    world: jax.Array     # [N] i32 interned world id
    peer: jax.Array      # [N] i32 dense peer id


def device_coord_clamp(x: jax.Array, size: int) -> jax.Array:
    """Subscription-cube quantizer on device (f32 → i64 labels).

    Mirrors the max-corner / sign-symmetric / 0→+size semantics of the
    golden host quantizer (cube_area.rs:23-44).
    """
    size_f = jnp.float32(size)
    a = jnp.abs(x)
    mult = jnp.where(x < 0, -1, 1).astype(jnp.int64)
    rounded = jnp.ceil(a / size_f) * size_f
    rounded = jnp.where(a == 0.0, size_f, rounded)
    exact = (jnp.mod(a, size_f) == 0.0) & (x != 0.0)
    ri = rounded.astype(jnp.int64)
    res = jnp.where(rounded > a, ri, ri + size)
    res = jnp.where(exact, a.astype(jnp.int64), res)
    # NaN → +size like the host quantizer (XLA's NaN→int cast is
    # platform-defined, so guard explicitly).
    return jnp.where(jnp.isnan(x), jnp.int64(size), res * mult)


_M1 = jnp.uint64(MIX_M1)
_M2 = jnp.uint64(MIX_M2)
_GOLDEN = jnp.uint64(MIX_GOLDEN)


def _mix(x: jax.Array) -> jax.Array:
    x = (x ^ (x >> jnp.uint64(30))) * _M1
    x = (x ^ (x >> jnp.uint64(27))) * _M2
    return x ^ (x >> jnp.uint64(31))


def device_spatial_keys(
    world: jax.Array, cubes: jax.Array, seed: int = 0
) -> jax.Array:
    """Device twin of spatial/hashing.spatial_keys: [N] i32 world ids +
    [N, 3] i64 cubes → [N] i64 sort keys."""
    h = _mix(jnp.uint64(seed) + _GOLDEN)
    h = _mix(h ^ world.astype(jnp.int64).view(jnp.uint64))
    h = _mix(h ^ cubes[..., 0].view(jnp.uint64))
    h = _mix(h ^ cubes[..., 1].view(jnp.uint64))
    h = _mix(h ^ cubes[..., 2].view(jnp.uint64))
    return h.view(jnp.int64)


def simulation_tick(
    state: EntityState,
    *,
    cube_size: int,
    k: int,
    dt: float = 0.05,
    bounds: float = 1000.0,
    seed: int = 0,
):
    """One tick: integrate → quantize → rebuild hash → resolve fan-out.

    Returns ``(new_state, targets, counts)`` where ``targets`` is
    [N, K] i32 peer ids each entity broadcasts to this tick (-1 = none;
    except-self), and ``counts`` the exact co-cube population including
    self (callers can detect K-overflow as counts > K).
    """
    n = state.position.shape[0]

    # 1. integrate, reflecting at ±bounds.
    pos = state.position + state.velocity * jnp.float32(dt)
    over = pos > bounds
    under = pos < -bounds
    pos = jnp.where(over, 2.0 * bounds - pos, pos)
    pos = jnp.where(under, -2.0 * bounds - pos, pos)
    vel = jnp.where(over | under, -state.velocity, state.velocity)

    # 2. quantize to subscription cubes.
    cubes = device_coord_clamp(pos, cube_size)

    # 3. per-tick spatial-hash rebuild: one sort.
    keys = device_spatial_keys(state.world, cubes, seed)
    order = jnp.argsort(keys)
    sorted_keys = keys[order]
    sorted_peer = state.peer[order]

    # 4. resolve every entity's broadcast set. Every entity is a row of
    # the sort it just participated in, so its run bounds come from a
    # vectorized segment scan + one scatter back through ``order`` —
    # no binary search (which would be 2 x log2(N) rounds of random
    # gathers, the dominant cost at 100K+ entities).
    p_idx = jnp.arange(n, dtype=jnp.int32)
    boundary = sorted_keys[1:] != sorted_keys[:-1]
    first = jnp.concatenate([jnp.ones((1,), bool), boundary])
    last = jnp.concatenate([boundary, jnp.ones((1,), bool)])
    run_start = jax.lax.cummax(jnp.where(first, p_idx, 0))
    run_end = jax.lax.cummin(
        jnp.where(last, p_idx + 1, jnp.int32(n)), reverse=True
    )
    lo = jnp.zeros(n, jnp.int32).at[order].set(run_start)
    hi = jnp.zeros(n, jnp.int32).at[order].set(run_end)
    counts = hi - lo

    offs = jnp.arange(k, dtype=jnp.int32)
    gidx = jnp.minimum(lo[:, None] + offs[None, :], n - 1)
    tgt = sorted_peer[gidx]
    valid = (offs[None, :] < counts[:, None]) & (tgt != state.peer[:, None])

    # 5. true k-nearest selection: order each entity's co-cube
    # candidates nearest-first by squared distance. Distance bits and
    # target pack into ONE int64 per candidate so the whole reorder is
    # a single row-sort — lax.top_k on [N, K] costs ~5x more on TPU
    # (measured) for the same result. IEEE bits of a non-negative f32
    # are order-preserving, invalid slots carry the all-ones bit
    # pattern (above +inf AND every NaN, so they sink below both), and
    # equal distances tie-break by peer id (deterministic). With cube
    # occupancy beyond K the window truncates at K candidates (callers
    # detect via counts > K); within it the result is the k nearest,
    # not sort-order happenstance.
    targets = jnp.where(valid, tgt, -1)
    sorted_pos = pos[order]
    cand = sorted_pos[gidx]  # [N, K, 3]
    d2 = jnp.sum((cand - pos[:, None, :]) ** 2, axis=-1).astype(jnp.float32)
    d2_bits = jax.lax.bitcast_convert_type(d2, jnp.uint32)
    # mask invalid slots at the BIT level: uint32 max exceeds even NaN
    # bit patterns, so a valid candidate with a NaN distance (NaN
    # positions are supported inputs — they quantize to cube +size)
    # still sorts before the -1 sentinels instead of after them
    d2_bits = jnp.where(valid, d2_bits, jnp.uint32(0xFFFFFFFF))
    packed = (d2_bits.astype(jnp.uint64) << jnp.uint64(32)) | (
        (targets + 1).astype(jnp.uint64) & jnp.uint64(0xFFFFFFFF)
    )
    packed = jnp.sort(packed, axis=1)
    targets = (packed & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32) - 1

    return EntityState(pos, vel, state.world, state.peer), targets, counts


def make_tick_fn(cube_size: int = 16, k: int = 32, dt: float = 0.05,
                 bounds: float = 1000.0):
    """Close the static params; returns a jittable ``fn(state)``."""
    return partial(simulation_tick, cube_size=cube_size, k=k, dt=dt,
                   bounds=bounds)


def example_state(n: int = 1024, n_worlds: int = 4, seed: int = 7) -> EntityState:
    """Deterministic small entity population for compile checks."""
    kp, kv = jax.random.split(jax.random.PRNGKey(seed))
    return EntityState(
        position=jax.random.uniform(
            kp, (n, 3), jnp.float32, minval=-900.0, maxval=900.0
        ),
        velocity=jax.random.uniform(
            kv, (n, 3), jnp.float32, minval=-40.0, maxval=40.0
        ),
        world=(jnp.arange(n, dtype=jnp.int32) % n_worlds),
        peer=jnp.arange(n, dtype=jnp.int32),
    )
