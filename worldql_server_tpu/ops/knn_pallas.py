"""Pallas TPU kernel: fused stencil + k-nearest selection for the tick.

The XLA formulation of the tick's neighbor resolve (ops/tick.py steps
4-5) materializes [N, 2K-1] candidate tables (run id, peer, distance,
packed key) in HBM and row-sorts them — several HBM round trips and,
depending on how XLA schedules the slice stack, dozens of kernel
launches. This kernel does the whole resolve in ONE launch: each grid
tile DMAs its sorted-order window (tile + K-1 halo on each side) from
HBM into VMEM, computes the 2K-1 masked squared distances on the VPU,
runs a key-value bitonic sorting network across the window, and writes
the K nearest peer ids straight to the output block.

Contract (identical to the XLA path, ops/tick.py):
* candidates are the ±(K-1) sort-order neighbors with the same run id;
* self and same-peer candidates fall to the ``peer != own`` mask
  (ExceptSelf);
* invalid slots carry the all-ones distance key, so they sink past
  every real candidate — including NaN distances (every NaN bit
  pattern < 0xFFFFFFFF), which therefore still broadcast;
* equal distances tie-break by peer id ascending (the network compares
  (distance bits, peer) lexicographically — same order as the XLA
  path's packed-u64 sort).

Mosaic constraints shape the layout (all measured/verified on v5e):
* everything is 2-D — 1-D selects trip an infinite lowering recursion;
* no 64-bit types inside the kernel (the repo's global x64 mode must
  not leak in — every literal is explicitly 32-bit);
* the sort dimension is the SUBLANE axis: candidates live in a
  [W, tile] matrix built by concatenating [1, tile] window slices, so
  the bitonic exchanges are sublane rolls (slice+concat, natively
  supported; ``pltpu.roll`` currently fails verification here);
* the kernel writes [K, tile] blocks of a transposed [K, N] output and
  the host wrapper transposes back.

Inputs are PADDED sorted columns (run-id pad is -1, so halo lanes
never match). The host wrapper pads N up to the tile multiple and
slices the result back. ``interpret=True`` is chosen automatically off
TPU, so the same kernel body runs under the CPU test suite.
"""

from __future__ import annotations

from functools import partial

from ..spatial import jaxconf  # noqa: F401  (must precede jax import)
import jax
import jax.numpy as jnp

# u32 all-ones distance sentinel (python int: a module-level jnp scalar
# would be captured as a device constant, which pallas_call rejects)
_INVALID = 0xFFFFFFFF


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


def _bitonic_kv(keys, vals):
    """Ascending bitonic sort along axis 0 (sublanes) of
    (keys u32, vals i32), comparing (key, val) lexicographically.
    Axis-0 length must be a power of two. Exchanges are XOR-partner
    rolls — slice+concat under the hood, no gathers, no lane-dim
    reshapes."""
    w = keys.shape[0]
    row = jax.lax.broadcasted_iota(jnp.int32, keys.shape, 0)
    size = 2
    while size <= w:
        dist = size // 2
        while dist >= 1:
            up = (row & size) == 0
            left = (row & dist) == 0
            pk = jnp.where(left, jnp.roll(keys, -dist, axis=0),
                           jnp.roll(keys, dist, axis=0))
            pv = jnp.where(left, jnp.roll(vals, -dist, axis=0),
                           jnp.roll(vals, dist, axis=0))
            own_gt = (keys > pk) | ((keys == pk) & (vals > pv))
            par_gt = (pk > keys) | ((pk == keys) & (pv > vals))
            # boolean algebra, not jnp.where: Mosaic rejects a select
            # whose BRANCHES are i1 ("unsupported bitwidth truncation")
            gt = (own_gt & left) | (par_gt & ~left)
            take = gt == up  # in an ascending block the left lane
            keys = jnp.where(take, pk, keys)  # keeps the smaller pair
            vals = jnp.where(take, pv, vals)
            dist //= 2
        size *= 2
    return keys, vals


def _win_size(tile: int, k: int) -> int:
    """Per-tile window: tile + both halos, rounded to the 128-lane
    Mosaic slice alignment. Single source of truth — the kernel's
    window reads and the host wrapper's padding must agree exactly."""
    return -(-(tile + 2 * (k - 1)) // 128) * 128


def _make_kernel(tile: int, k: int, interpret: bool):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    w = 2 * k - 1
    wp = _next_pow2(w)
    win = _win_size(tile, k)

    def tile_body(rid_w, peer_w, x_w, y_w, z_w):
        """One tile's resolve from its [1, win] VMEM windows."""

        def at(buf, s):
            # slice [1, tile] at window offset s, re-materialized at
            # lane offset 0 via a roll: Mosaic's concat cannot join
            # operands whose lane offsets differ ("result/input offset
            # mismatch on non-concat dimension"), and the row concat
            # below needs offset-0 operands
            if s == 0:  # a 0-shift roll lowers to an empty slice
                return buf[:, :tile]
            return jnp.roll(buf, -s, axis=1)[:, :tile]

        rid0 = at(rid_w, k - 1)     # [1, tile] self rows
        peer0 = at(peer_w, k - 1)
        x0 = at(x_w, k - 1)
        y0 = at(y_w, k - 1)
        z0 = at(z_w, k - 1)

        key_rows, val_rows = [], []
        for s in range(wp):
            if s < w:
                same = (at(rid_w, s) == rid0) & (at(peer_w, s) != peer0) \
                    & (rid0 >= 0)
                dx = at(x_w, s) - x0
                dy = at(y_w, s) - y0
                dz = at(z_w, s) - z0
                d2 = dx * dx + dy * dy + dz * dz
                key_rows.append(jnp.where(
                    same, jax.lax.bitcast_convert_type(d2, jnp.uint32),
                    jnp.uint32(_INVALID),
                ))
                val_rows.append(
                    jnp.where(same, at(peer_w, s), jnp.int32(-1))
                )
            else:
                key_rows.append(
                    jnp.full((1, tile), _INVALID, jnp.uint32)
                )
                val_rows.append(jnp.full((1, tile), -1, jnp.int32))
        keys = jnp.concatenate(key_rows, axis=0)   # [wp, tile]
        vals = jnp.concatenate(val_rows, axis=0)
        _, vals = _bitonic_kv(keys, vals)
        return vals[:k, :]

    def kernel(rid_ref, peer_ref, x_ref, y_ref, z_ref, out_ref):
        # One program, tiles as an in-kernel loop: this environment's
        # Mosaic fails to legalize ANY grid-ful pallas_call ('func.
        # return'), and a TPU grid is a sequential loop on the core
        # anyway. Inputs are VMEM-resident, so the per-tile window read
        # is a dynamic VMEM slice, not a DMA.
        n_tiles = out_ref.shape[1] // tile

        def body(i, carry):
            start = i * tile
            vals = tile_body(
                rid_ref[:, pl.ds(start, win)],
                peer_ref[:, pl.ds(start, win)],
                x_ref[:, pl.ds(start, win)],
                y_ref[:, pl.ds(start, win)],
                z_ref[:, pl.ds(start, win)],
            )
            out_ref[:, pl.ds(start, tile)] = vals
            return carry

        jax.lax.fori_loop(jnp.int32(0), jnp.int32(n_tiles), body,
                          jnp.int32(0))

    def call(rid_p, peer_p, x_p, y_p, z_p, n_pad):
        vm = pl.BlockSpec(memory_space=pltpu.VMEM)
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((k, n_pad), jnp.int32),
            in_specs=[vm] * 5,
            out_specs=vm,
            interpret=interpret,
        )(rid_p, peer_p, x_p, y_p, z_p)

    return call


@partial(jax.jit, static_argnames=("k", "tile", "interpret"))
def _knn_jit(rid, peer, pos, k, tile, interpret):
    n = rid.shape[0]
    n_pad = -(-n // tile) * tile
    halo = k - 1
    win = _win_size(tile, k)
    pad = (halo, n_pad - n + win - halo)

    def prep(a, fill=0):
        return jnp.pad(a, pad, constant_values=fill)[None, :]

    cols = (prep(rid, -1), prep(peer, -1),
            prep(pos[:, 0]), prep(pos[:, 1]), prep(pos[:, 2]))

    # chunk the single-program kernel so its VMEM residency (inputs +
    # the [K, chunk] output block) stays a few MB; the last chunk is
    # sized to what remains, not the full stride
    stride = min(n_pad, 64 * tile)
    call = _make_kernel(tile, k, interpret)
    outs = []
    for c0 in range(0, n_pad, stride):
        this = min(stride, n_pad - c0)
        outs.append(call(*(c[:, c0:c0 + this + win] for c in cols), this))
    out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
    return out.T[:n]


def knn_select(rid, peer, pos, *, k: int, tile: int = 512,
               interpret: bool | None = None):
    """[N] run ids (i32, sorted order; -1 = masked row), [N] peers,
    [N, 3] f32 positions → [N, K] nearest co-run peers per row,
    -1-padded, nearest-first. Fused Pallas path; semantically identical
    to the XLA stencil in ops/tick.py."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _knn_jit(
        rid.astype(jnp.int32), peer.astype(jnp.int32),
        pos.astype(jnp.float32), k, tile, interpret,
    )
