"""Device-resident simulation ops: the fully-on-device tick loop used
by the crowd benchmarks (BASELINE configs 2/3/5) and the graft entry.
"""

from .tick import (
    EntityState,
    device_coord_clamp,
    device_spatial_keys,
    make_tick_fn,
    simulation_tick,
)

__all__ = [
    "EntityState",
    "device_coord_clamp",
    "device_spatial_keys",
    "make_tick_fn",
    "simulation_tick",
]
