"""Sender worker process: one shard of the delivery plane.

Each worker is a plain SYNCHRONOUS process — no asyncio, no event
loop, no Peer objects (the ``worker-unsafe-delivery`` lint rule keeps
it that way): it drains its shared-memory ring of
``(frame_bytes, slot_ids)`` records and pushes frames out of the
sockets it OWNS —

* WebSocket peers arrive as raw TCP fds passed over the control
  channel at handshake (``socket.recv_fds``); the worker writes
  complete server→client frames (``ws_framing``) non-blocking with a
  bounded per-socket backlog, mirroring the parent's
  ``_WRITE_HARD_LIMIT`` eviction semantics.
* ZeroMQ peers arrive as connect-back endpoints; the worker connects
  its OWN ``PUSH`` socket (sends never touch the parent's context).

The worker never decides membership: a failed/overflowing peer is
closed locally and REPORTED (``{"op": "fail"}``) — the parent's
authoritative PeerMap performs the eviction, so ``on_peer_removed``
and staleness semantics are identical to single-process mode.

Control channel: one ``AF_UNIX`` ``SOCK_SEQPACKET`` connection (packet
boundaries preserved, fd passing supported). JSON packets both ways —
control is not the hot path; the hot path is the pickle-free ring.
"""

from __future__ import annotations

import json
import os
import select
import signal
import socket
import time

from .ring import Ring
from ..transports.ws_framing import ws_binary_frame

#: per-socket outbound backlog bound — a consumer that lets this much
#: buffer is dead-or-pathological and is evicted (same constant and
#: rationale as transports/websocket.py _WRITE_HARD_LIMIT)
PENDING_HARD_LIMIT = 8 << 20

#: worker→parent cumulative-stats cadence (seconds)
STATS_INTERVAL = 0.25


class _WsSink:
    """One handed-off WebSocket TCP socket: non-blocking whole-frame
    writes with an ordered backlog for partial sends."""

    kind = "ws"
    __slots__ = ("sock", "pending", "pending_bytes")

    def __init__(self, fd: int):
        self.sock = socket.socket(fileno=fd)
        self.sock.setblocking(False)
        self.pending: list[memoryview] = []
        self.pending_bytes = 0

    def send(self, frame: bytes) -> str:
        if self.pending:
            # order over speed: never bypass the backlog
            self.pending.append(memoryview(frame))
            self.pending_bytes += len(frame)
            if self.pending_bytes > PENDING_HARD_LIMIT:
                return "overflow"
            return "ok"
        try:
            n = self.sock.send(frame)
        except (BlockingIOError, InterruptedError):
            n = 0
        except OSError:
            return "fail"
        if n < len(frame):
            self.pending.append(memoryview(frame)[n:])
            self.pending_bytes += len(frame) - n
        return "ok"

    def flush(self) -> str:
        while self.pending:
            mv = self.pending[0]
            try:
                n = self.sock.send(mv)
            except (BlockingIOError, InterruptedError):
                return "ok"
            except OSError:
                return "fail"
            self.pending_bytes -= n
            if n == len(mv):
                self.pending.pop(0)
            else:
                self.pending[0] = mv[n:]
                return "ok"
        return "ok"

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class _ZmqSink:
    """One worker-owned connect-back PUSH socket (outgoing.rs:95-118
    ownership moved into the shard)."""

    kind = "zmq"
    __slots__ = ("sock",)

    def __init__(self, ctx, endpoint: str):
        import zmq

        self.sock = ctx.socket(zmq.PUSH)
        self.sock.setsockopt(zmq.LINGER, 0)
        # deep HWM: the reference's relay channel is unbounded below
        # failure; hitting this is treated as a failed send (evict)
        self.sock.setsockopt(zmq.SNDHWM, 65536)
        self.sock.connect(endpoint)

    def send(self, payload: bytes) -> str:
        import zmq

        try:
            self.sock.send(payload, zmq.NOBLOCK)
        except zmq.Again:
            return "overflow"
        except Exception:
            return "fail"
        return "ok"

    def flush(self) -> str:
        return "ok"

    def close(self) -> None:
        try:
            self.sock.close(linger=0)
        except Exception:
            pass


def _ctl_send(ctl: socket.socket, msg: dict, critical: bool = True) -> None:
    """One control packet to the parent. Stats packets are best-effort
    (a full buffer drops the sample); fail/ready packets retry briefly
    — losing one would leak a dead peer from the map until the
    staleness sweep."""
    data = json.dumps(msg).encode()
    deadline = time.monotonic() + (1.0 if critical else 0.0)
    while True:
        try:
            ctl.send(data)
            return
        except (BlockingIOError, InterruptedError):
            if time.monotonic() >= deadline:
                return
            select.select([], [ctl], [], 0.01)
        except OSError:
            return


def worker_main(worker_id: int, control_path: str, ring_name: str) -> None:
    """Process entry (multiprocessing spawn target)."""
    # the parent owns lifecycle: SIGINT storms (Ctrl-C to the group)
    # must not kill a worker mid-drain; SIGTERM requests a clean stop
    stopping = {"flag": False}
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    signal.signal(signal.SIGTERM, lambda *_: stopping.__setitem__("flag", True))

    ctl = socket.socket(socket.AF_UNIX, socket.SOCK_SEQPACKET)
    ctl.connect(control_path)
    ctl.setblocking(False)
    ring = Ring.attach(ring_name)
    sinks: dict[int, object] = {}
    zmq_ctx = None
    stats = {
        "records": 0,      # ring records consumed
        "deliveries": 0,   # frame×peer sends attempted
        "sends_ok": 0,
        "send_errors": 0,
        "bytes": 0,
        "evictions": 0,    # peers this worker reported as failed
        "drain_ms": 0.0,   # wall of the last non-empty drain burst
    }
    _ctl_send(ctl, {"op": "ready", "pid": os.getpid(), "worker": worker_id})
    last_stats = time.monotonic()

    def drop_sink(slot: int, reason: str) -> None:
        sink = sinks.pop(slot, None)
        if sink is not None:
            sink.close()
        stats["evictions"] += 1
        _ctl_send(ctl, {"op": "fail", "slot": slot, "reason": reason})

    def handle_control(data: bytes, fds: list[int]) -> bool:
        """One parent packet; False = stop requested."""
        nonlocal zmq_ctx
        try:
            msg = json.loads(data)
        except ValueError:
            return True
        op = msg.get("op")
        if op == "add":
            slot = msg["slot"]
            try:
                if msg["kind"] == "ws" and fds:
                    sinks[slot] = _WsSink(fds[0])
                    fds.clear()  # consumed
                elif msg["kind"] == "zmq":
                    if zmq_ctx is None:
                        import zmq

                        zmq_ctx = zmq.Context()
                    sinks[slot] = _ZmqSink(zmq_ctx, msg["endpoint"])
            except Exception:
                # an unconnectable sink is a failed peer, not a dead
                # worker: report it and keep the shard serving
                stats["evictions"] += 1
                _ctl_send(
                    ctl, {"op": "fail", "slot": slot,
                          "reason": "send_failed"},
                )
        elif op == "remove":
            sink = sinks.pop(msg["slot"], None)
            if sink is not None:
                sink.close()
        elif op == "stop":
            return False
        return True

    try:
        while True:
            progressed = False
            # 1. drain the ring (bounded burst keeps control responsive)
            t0 = time.perf_counter()
            for _ in range(512):
                rec = ring.read()
                if rec is None:
                    break
                progressed = True
                frame, slots = rec
                stats["records"] += 1
                ws_frame = None
                for slot in slots:
                    sink = sinks.get(slot)
                    if sink is None:
                        continue  # removed while the record was in flight
                    stats["deliveries"] += 1
                    if sink.kind == "ws":
                        if ws_frame is None:
                            # framed ONCE per record, shared by every
                            # WS recipient in the slot list
                            ws_frame = ws_binary_frame(frame)
                        status = sink.send(ws_frame)
                        stats["bytes"] += len(ws_frame)
                    else:
                        status = sink.send(frame)
                        stats["bytes"] += len(frame)
                    if status == "ok":
                        stats["sends_ok"] += 1
                    else:
                        stats["send_errors"] += 1
                        drop_sink(
                            slot,
                            "overflow" if status == "overflow"
                            else "send_failed",
                        )
            if progressed:
                stats["drain_ms"] = (time.perf_counter() - t0) * 1e3
            # 2. flush partial-write backlogs
            for slot, sink in list(sinks.items()):
                if sink.kind == "ws" and sink.pending:
                    if sink.flush() == "fail":
                        stats["send_errors"] += 1
                        drop_sink(slot, "send_failed")
            # 3. control packets
            stop_req = stopping["flag"]
            while True:
                try:
                    data, fds, _flags, _addr = socket.recv_fds(ctl, 65536, 8)
                except (BlockingIOError, InterruptedError):
                    break
                except OSError:
                    data, fds = b"", []
                if not data:
                    return  # parent gone — nothing left to serve
                if not handle_control(data, list(fds)):
                    stop_req = True
            # 4. periodic cumulative stats
            now = time.monotonic()
            if now - last_stats >= STATS_INTERVAL:
                last_stats = now
                _ctl_send(
                    ctl,
                    {"op": "stats", "worker": worker_id, "peers": len(sinks),
                     "ring_pending": ring.pending_bytes(), **stats},
                    critical=False,
                )
            if stop_req:
                stopping["flag"] = True
                # stop once the ring is drained and backlogs flushed
                # (bounded below by the parent's join timeout)
                if ring.pending_bytes() == 0 and not any(
                    s.kind == "ws" and s.pending for s in sinks.values()
                ):
                    break
                continue
            # 5. idle wait: the ring is empty — sleep on control
            # traffic / writability instead of spinning
            if not progressed:
                wlist = [
                    s.sock for s in sinks.values()
                    if s.kind == "ws" and s.pending
                ]
                try:
                    select.select([ctl], wlist, [], 0.002)
                except OSError:
                    pass
    finally:
        _ctl_send(
            ctl,
            {"op": "stats", "worker": worker_id, "peers": len(sinks),
             "ring_pending": ring.pending_bytes(), **stats},
            critical=False,
        )
        for sink in sinks.values():
            sink.close()
        if zmq_ctx is not None:
            zmq_ctx.term()
        ring.close()
        ctl.close()
