"""Sender worker process: one shard of the delivery plane.

Each worker is a plain SYNCHRONOUS process — no asyncio, no event
loop, no Peer objects (the ``worker-unsafe-delivery`` lint rule keeps
it that way): it drains its shared-memory ring of
``(frame_bytes, slot_ids)`` records and pushes frames out of the
sockets it OWNS —

* WebSocket peers arrive as raw TCP fds passed over the control
  channel at handshake (``socket.recv_fds``); the worker writes
  complete server→client frames (``ws_framing``) non-blocking with a
  bounded per-socket backlog, mirroring the parent's
  ``_WRITE_HARD_LIMIT`` eviction semantics.
* ZeroMQ peers arrive as connect-back endpoints; the worker connects
  its OWN ``PUSH`` socket (sends never touch the parent's context).

The worker never decides membership: a failed/overflowing peer is
closed locally and REPORTED (``{"op": "fail"}``) — the parent's
authoritative PeerMap performs the eviction, so ``on_peer_removed``
and staleness semantics are identical to single-process mode.

Control channel: one ``AF_UNIX`` ``SOCK_SEQPACKET`` connection (packet
boundaries preserved, fd passing supported). JSON packets both ways —
control is not the hot path; the hot path is the pickle-free ring.

Telemetry (ISSUE 7): each ring record carries two CLOCK_MONOTONIC
stamps (frame-clock ingress + ring write, see delivery/ring.py); the
worker closes them at socket-write-complete into two cumulative local
histograms — ``e2e`` (ring write → write complete: ring dwell + write
time, the per-worker ``delivery.worker.<i>.e2e_ms`` series) and
``frame_e2e`` (router-dispatch/flush-start → write complete: the
honest fan-out frame clock) — plus a bounded buffer of per-record span
SEGMENTS the parent stitches under ``tick.deliver`` in the flight
recorder. Both ride the periodic stats packet; the parent diffs the
cumulative counts into its registry, so worker restarts never reset a
merged series. Caveat: a frame parked in a WS backlog closes its clock
when the flushed tail finally drains (tracked per pending buffer), so
slow-consumer tails land in the histograms instead of hiding behind
the non-blocking send's immediate return.
"""

from __future__ import annotations

import json
import os
import select
import signal
import socket
import time

from .ring import Ring
from ..robustness import failpoints
from ..robustness.failpoints import FailpointError
from ..transports.ws_framing import ws_binary_frame

#: per-socket outbound backlog bound — a consumer that lets this much
#: buffer is dead-or-pathological and is evicted (same constant and
#: rationale as transports/websocket.py _WRITE_HARD_LIMIT)
PENDING_HARD_LIMIT = 8 << 20

#: worker→parent cumulative-stats cadence (seconds)
STATS_INTERVAL = 0.25

#: span segments buffered per stats interval — the stitching detail
#: cap; past it records skip per-slot timing too (the hot path stays
#: two clock reads per record, not two per send)
SEGMENT_CAP = 128

#: histogram bucket upper bounds in ms — MUST mirror
#: engine/metrics.py LATENCY_BUCKETS_MS (pinned by
#: tests/test_worker_telemetry.py) so the parent can merge cumulative
#: bucket counts straight into its registry. Duplicated rather than
#: imported: pulling engine/* into the worker process would drag the
#: whole server object graph through every spawn.
BUCKETS_MS = (
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 25000.0, 50000.0, 100000.0,
    250000.0,
)


class _Hist:
    """Cumulative fixed-bucket latency histogram (worker-local, no
    locks — the worker is single-threaded by design)."""

    __slots__ = ("counts", "total", "sum_ms", "max_ms")

    def __init__(self):
        self.counts = [0] * (len(BUCKETS_MS) + 1)
        self.total = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0

    def observe(self, value_ms: float) -> None:
        i = 0
        for i, bound in enumerate(BUCKETS_MS):  # noqa: B007
            if value_ms <= bound:
                break
        else:
            i = len(BUCKETS_MS)
        self.counts[i] += 1
        self.total += 1
        self.sum_ms += value_ms
        if value_ms > self.max_ms:
            self.max_ms = value_ms

    def packet(self) -> dict:
        """Cumulative snapshot for the stats packet (the parent diffs
        against the previous packet, so restarts re-zero cleanly)."""
        return {
            "counts": self.counts, "total": self.total,
            "sum_ms": round(self.sum_ms, 3),
            "max_ms": round(self.max_ms, 3),
        }


class _FrameClock:
    """Shared completion state for one ring record's backlogged tail:
    observed ONCE, at the first flush that fully drains a sink this
    record pended on (typically the one slow consumer the tail
    exists for)."""

    __slots__ = ("t_ingress", "t_write", "done")

    def __init__(self, t_ingress: int, t_write: int):
        self.t_ingress = t_ingress
        self.t_write = t_write
        self.done = False


class _WsSink:
    """One handed-off WebSocket TCP socket: non-blocking whole-frame
    writes with an ordered backlog for partial sends. Backlogged frames
    carry their record's :class:`_FrameClock` so the e2e close happens
    when the bytes actually drain, not when they were parked."""

    kind = "ws"
    __slots__ = ("sock", "pending", "pending_bytes")

    def __init__(self, fd: int):
        self.sock = socket.socket(fileno=fd)
        self.sock.setblocking(False)
        self.pending: list[list] = []   # [memoryview, _FrameClock | None]
        self.pending_bytes = 0

    def send(self, frame: bytes, clock=None) -> str:
        if self.pending:
            # order over speed: never bypass the backlog
            self.pending.append([memoryview(frame), clock])
            self.pending_bytes += len(frame)
            if self.pending_bytes > PENDING_HARD_LIMIT:
                return "overflow"
            return "ok"
        try:
            n = self.sock.send(frame)
        except (BlockingIOError, InterruptedError):
            n = 0
        except OSError:
            return "fail"
        if n < len(frame):
            self.pending.append([memoryview(frame)[n:], clock])
            self.pending_bytes += len(frame) - n
        return "ok"

    def flush(self, on_done=None) -> str:
        while self.pending:
            mv, clock = self.pending[0]
            try:
                n = self.sock.send(mv)
            except (BlockingIOError, InterruptedError):
                return "ok"
            except OSError:
                return "fail"
            self.pending_bytes -= n
            if n == len(mv):
                self.pending.pop(0)
                if clock is not None and on_done is not None:
                    on_done(clock)
            else:
                self.pending[0][0] = mv[n:]
                return "ok"
        return "ok"

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class _ZmqSink:
    """One worker-owned connect-back PUSH socket (outgoing.rs:95-118
    ownership moved into the shard)."""

    kind = "zmq"
    __slots__ = ("sock",)

    def __init__(self, ctx, endpoint: str):
        import zmq

        self.sock = ctx.socket(zmq.PUSH)
        self.sock.setsockopt(zmq.LINGER, 0)
        # deep HWM: the reference's relay channel is unbounded below
        # failure; hitting this is treated as a failed send (evict)
        self.sock.setsockopt(zmq.SNDHWM, 65536)
        self.sock.connect(endpoint)

    def send(self, payload: bytes) -> str:
        import zmq

        try:
            self.sock.send(payload, zmq.NOBLOCK)
        except zmq.Again:
            return "overflow"
        except Exception:
            return "fail"
        return "ok"

    def flush(self) -> str:
        return "ok"

    def close(self) -> None:
        try:
            self.sock.close(linger=0)
        except Exception:
            pass


def _ctl_send(ctl: socket.socket, msg: dict, critical: bool = True) -> None:
    """One control packet to the parent. Stats packets are best-effort
    (a full buffer drops the sample); fail/ready packets retry briefly
    — losing one would leak a dead peer from the map until the
    staleness sweep."""
    data = json.dumps(msg).encode()
    deadline = time.monotonic() + (1.0 if critical else 0.0)
    while True:
        try:
            ctl.send(data)
            return
        except (BlockingIOError, InterruptedError):
            if time.monotonic() >= deadline:
                return
            select.select([], [ctl], [], 0.01)
        except OSError:
            return


def worker_main(worker_id: int, control_path: str, ring_name: str,
                failpoints_spec: str = "",
                failpoints_seed: int | None = None) -> None:
    """Process entry (multiprocessing spawn target)."""
    # the parent owns lifecycle: SIGINT storms (Ctrl-C to the group)
    # must not kill a worker mid-drain; SIGTERM requests a clean stop
    stopping = {"flag": False}
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    signal.signal(signal.SIGTERM, lambda *_: stopping.__setitem__("flag", True))

    if failpoints_spec:
        # the parent's spec rides the spawn args (the registry is
        # per-process): chaos runs exercise worker-side sites like
        # `delivery.worker_send` deterministically, and the fired
        # counts report back via the stats packet so the parent's
        # failpoints gauge audits the WHOLE plane
        try:
            failpoints.registry.configure(
                failpoints_spec, seed=failpoints_seed
            )
        except Exception:
            pass  # a bad spec must not kill the shard

    ctl = socket.socket(socket.AF_UNIX, socket.SOCK_SEQPACKET)
    ctl.connect(control_path)
    ctl.setblocking(False)
    ring = Ring.attach(ring_name)
    sinks: dict[int, object] = {}
    zmq_ctx = None
    stats = {
        "records": 0,      # ring records consumed
        "deliveries": 0,   # frame×peer sends attempted
        "sends_ok": 0,
        "send_errors": 0,
        "bytes": 0,
        "evictions": 0,    # peers this worker reported as failed
        "drain_ms": 0.0,   # wall of the last non-empty drain burst
    }
    e2e_hist = _Hist()        # ring write → socket-write-complete
    frame_hist = _Hist()      # frame-clock ingress → write-complete
    segments: list[list] = []  # span segments for parent-side stitching

    def tail_done(clock: _FrameClock) -> None:
        """A backlogged frame's bytes fully drained — close its clocks
        (once per record; the first draining sink wins)."""
        if clock.done:
            return
        clock.done = True
        now = time.monotonic_ns()
        e2e_hist.observe((now - clock.t_write) / 1e6)
        if clock.t_ingress:
            frame_hist.observe((now - clock.t_ingress) / 1e6)

    _ctl_send(ctl, {"op": "ready", "pid": os.getpid(), "worker": worker_id})
    last_stats = time.monotonic()

    def drop_sink(slot: int, reason: str) -> None:
        sink = sinks.pop(slot, None)
        if sink is not None:
            sink.close()
        stats["evictions"] += 1
        _ctl_send(ctl, {"op": "fail", "slot": slot, "reason": reason})

    def handle_control(data: bytes, fds: list[int]) -> bool:
        """One parent packet; False = stop requested."""
        nonlocal zmq_ctx
        try:
            msg = json.loads(data)
        except ValueError:
            return True
        op = msg.get("op")
        if op == "add":
            slot = msg["slot"]
            try:
                if msg["kind"] == "ws" and fds:
                    sinks[slot] = _WsSink(fds[0])
                    fds.clear()  # consumed
                elif msg["kind"] == "zmq":
                    if zmq_ctx is None:
                        import zmq

                        zmq_ctx = zmq.Context()
                    sinks[slot] = _ZmqSink(zmq_ctx, msg["endpoint"])
            except Exception:
                # an unconnectable sink is a failed peer, not a dead
                # worker: report it and keep the shard serving
                stats["evictions"] += 1
                _ctl_send(
                    ctl, {"op": "fail", "slot": slot,
                          "reason": "send_failed"},
                )
        elif op == "remove":
            sink = sinks.pop(msg["slot"], None)
            if sink is not None:
                sink.close()
        elif op == "stop":
            return False
        return True

    try:
        while True:
            progressed = False
            # 1. drain the ring (bounded burst keeps control responsive)
            t0 = time.perf_counter()
            for _ in range(512):
                rec = ring.read_record()
                if rec is None:
                    break
                progressed = True
                frame, slots, t_ingress, t_write = rec
                t_deq = time.monotonic_ns()
                try:
                    # slow-consumer-tail chaos site (delay): wedges the
                    # shard's drain so stats_age/degraded detection and
                    # ring-full backpressure are testable
                    failpoints.fire("delivery.worker_send")
                except FailpointError:
                    pass  # only delay is meaningful at this site
                stats["records"] += 1
                ws_frame = None
                clock = None
                # per-slot timing only while a stitch segment is still
                # wanted this interval — past the cap the hot path pays
                # two clock reads per RECORD, not two per send
                want_detail = len(segments) < SEGMENT_CAP
                slow_slot, slow_ms = -1, 0.0
                for slot in slots:
                    sink = sinks.get(slot)
                    if sink is None:
                        continue  # removed while the record was in flight
                    stats["deliveries"] += 1
                    ts = time.monotonic_ns() if want_detail else 0
                    if sink.kind == "ws":
                        if ws_frame is None:
                            # framed ONCE per record, shared by every
                            # WS recipient in the slot list
                            ws_frame = ws_binary_frame(frame)
                        if sink.pending and clock is None:
                            clock = _FrameClock(t_ingress, t_write)
                        status = sink.send(ws_frame, clock)
                        stats["bytes"] += len(ws_frame)
                        if sink.pending and clock is None:
                            # pended on THIS send: re-tag the entry so
                            # the flush closes the record's clock
                            clock = _FrameClock(t_ingress, t_write)
                            sink.pending[-1][1] = clock
                    else:
                        status = sink.send(frame)
                        stats["bytes"] += len(frame)
                    if want_detail:
                        dt = (time.monotonic_ns() - ts) / 1e6
                        if dt >= slow_ms:
                            slow_slot, slow_ms = slot, dt
                    if status == "ok":
                        stats["sends_ok"] += 1
                    else:
                        stats["send_errors"] += 1
                        drop_sink(
                            slot,
                            "overflow" if status == "overflow"
                            else "send_failed",
                        )
                t_done = time.monotonic_ns()
                if clock is None:
                    # every sink took the bytes now — close the clocks
                    e2e_hist.observe((t_done - t_write) / 1e6)
                    if t_ingress:
                        frame_hist.observe((t_done - t_ingress) / 1e6)
                # else: a WS backlog holds the tail; flush closes it
                if want_detail:
                    segments.append([
                        t_write,
                        round((t_deq - t_write) / 1e6, 3),   # ring dwell
                        round((t_done - t_deq) / 1e6, 3),    # write time
                        len(slots), slow_slot, round(slow_ms, 3),
                    ])
            if progressed:
                stats["drain_ms"] = (time.perf_counter() - t0) * 1e3
            # 2. flush partial-write backlogs
            for slot, sink in list(sinks.items()):
                if sink.kind == "ws" and sink.pending:
                    if sink.flush(tail_done) == "fail":
                        stats["send_errors"] += 1
                        drop_sink(slot, "send_failed")
            # 3. control packets
            stop_req = stopping["flag"]
            while True:
                try:
                    data, fds, _flags, _addr = socket.recv_fds(ctl, 65536, 8)
                except (BlockingIOError, InterruptedError):
                    break
                except OSError:
                    data, fds = b"", []
                if not data:
                    return  # parent gone — nothing left to serve
                if not handle_control(data, list(fds)):
                    stop_req = True
            # 4. periodic cumulative stats (+ telemetry: cumulative
            # e2e histograms the parent diffs into /metrics, drained
            # span segments for flight-recorder stitching, and this
            # process's failpoint fire counts for the plane-wide audit)
            now = time.monotonic()
            if now - last_stats >= STATS_INTERVAL:
                last_stats = now
                packet = {
                    "op": "stats", "worker": worker_id,
                    "peers": len(sinks),
                    "ring_pending": ring.pending_bytes(), **stats,
                    "e2e": e2e_hist.packet(),
                    "frame_e2e": frame_hist.packet(),
                }
                if segments:
                    packet["segments"] = segments
                    segments = []
                fired = failpoints.registry.fired_counts()
                if fired:
                    packet["fp"] = fired
                _ctl_send(ctl, packet, critical=False)
            if stop_req:
                stopping["flag"] = True
                # stop once the ring is drained and backlogs flushed
                # (bounded below by the parent's join timeout)
                if ring.pending_bytes() == 0 and not any(
                    s.kind == "ws" and s.pending for s in sinks.values()
                ):
                    break
                continue
            # 5. idle wait: the ring is empty — sleep on control
            # traffic / writability instead of spinning
            if not progressed:
                wlist = [
                    s.sock for s in sinks.values()
                    if s.kind == "ws" and s.pending
                ]
                try:
                    select.select([ctl], wlist, [], 0.002)
                except OSError:
                    pass
    finally:
        final = {
            "op": "stats", "worker": worker_id, "peers": len(sinks),
            "ring_pending": ring.pending_bytes(), **stats,
            "e2e": e2e_hist.packet(), "frame_e2e": frame_hist.packet(),
        }
        if segments:
            final["segments"] = segments
        fired = failpoints.registry.fired_counts()
        if fired:
            final["fp"] = fired
        _ctl_send(ctl, final, critical=False)
        for sink in sinks.values():
            sink.close()
        if zmq_ctx is not None:
            zmq_ctx.term()
        ring.close()
        ctl.close()
