"""Parent-side delivery plane: shard bookkeeping, worker lifecycle,
frame routing.

The parent stays authoritative for membership: transports register a
peer here at handshake (:meth:`DeliveryPlane.adopt`) and the plane
rebinds the peer's write paths so EVERY outbound frame — tick fan-out,
PeerConnect/Disconnect broadcasts, router replies — rides the owning
shard's ring. Workers never mutate the PeerMap; they report failures
over the control channel and the parent evicts through the normal
``PeerMap.remove`` path (``on_peer_removed`` + staleness semantics
unchanged).

Supervision mirrors robustness/supervisor.py discipline for processes:
a dead worker's peers are evicted with reason ``worker_lost``, its ring
lane is reclaimed, and the worker restarts with exponential backoff
within a budget (healthy-run refund). Budget exhaustion DEGRADES — the
shard is retired, new peers adopt onto surviving shards (or fall back
to the in-process pump when none survive) — it never wedges the tick
pipeline.
"""

from __future__ import annotations

import asyncio
import json
import logging
import multiprocessing
import os
import socket
import struct
import tempfile
import time
import uuid as uuid_mod
from array import array
from collections import deque
from typing import Callable

from ..observability.spans import Tracer
from ..robustness import failpoints
from ..robustness.failpoints import FailpointError
from .ring import Ring
from .worker import STATS_INTERVAL, worker_main

logger = logging.getLogger(__name__)

OnPeerLost = Callable[[uuid_mod.UUID, str], None]

#: a worker whose stats push is older than this many control-channel
#: intervals is wedged-but-alive: the process exists, the drain loop
#: does not — the delivery /healthz block marks it degraded (before
#: this, only a DEAD worker looked unhealthy)
STATS_STALE_INTERVALS = 3

#: worker span segments retained for flight-recorder stitching —
#: enough for several ticks of fan-out detail at the segment cap,
#: bounded so an idle /debug/ticks ring never pins stale history
SEGMENT_RETENTION = 2048

#: bounded waits before a frame is DROPPED (and counted) rather than
#: wedging the caller: the sync fast path (event loop, per-broadcast)
#: spins briefly; the async batch path yields to the loop for longer.
SYNC_WAIT_S = 0.002
ASYNC_WAIT_S = 0.25
#: a worker alive this long refunds its restart budget (supervisor.py
#: healthy-run discipline)
HEALTHY_RUN_S = 60.0


class _Shard:
    __slots__ = (
        "idx", "gen", "ring", "proc", "ctl", "alive", "retired",
        "restarts", "born", "peers", "slots", "next_slot", "reader",
        "stats", "stats_at",
    )

    def __init__(self, idx: int):
        self.idx = idx
        self.gen = 0
        self.ring: Ring | None = None
        self.proc = None
        self.ctl: socket.socket | None = None
        self.alive = False
        self.retired = False          # budget exhausted — never restarts
        self.restarts = 0
        self.born = 0.0
        self.peers: dict[uuid_mod.UUID, int] = {}
        self.slots: dict[int, uuid_mod.UUID] = {}
        self.next_slot = 0
        self.reader: asyncio.Task | None = None
        self.stats: dict = {}
        self.stats_at = 0.0           # monotonic time of the last push


class DeliveryPlane:
    def __init__(
        self,
        config,
        metrics=None,
        tracer: Tracer | None = None,
        on_peer_lost: OnPeerLost | None = None,
    ):
        self.n_workers = config.delivery_workers
        self.ring_bytes = config.delivery_ring_bytes
        self.metrics = metrics
        self.tracer = tracer if tracer is not None else Tracer()
        self.on_peer_lost = on_peer_lost
        # Optional drop hook (--interest on): called with the affected
        # peer UUIDs whenever deliver() sheds a frame on a full/dead
        # ring. The ONE observability point the in-process pump and the
        # worker plane share — the server wires both it and the
        # PeerMap's on_frame_loss to InterestManager.mark_resync, so a
        # ring drop forces the peer's next frame full exactly like a
        # local send error does.
        self.on_frame_drop = None
        self._budget = config.supervisor_budget
        self._backoff = config.supervisor_backoff
        # worker processes arm their own failpoint registry from the
        # same spec (spawn args) — worker-side sites fire there and
        # report back for the plane-wide audit
        self._failpoints_spec = getattr(config, "failpoints", "")
        self._failpoints_seed = getattr(config, "failpoints_seed", None)
        self._shards: list[_Shard] = []
        self._dir: str | None = None
        self._ctx = multiprocessing.get_context("spawn")
        self._stopping = False
        self.ring_drops = 0
        self.frames_submitted = 0
        #: worker-reported span segments awaiting flight-recorder
        #: stitching: (worker, t_write_ns, dwell_ms, write_ms, slots,
        #: slow_slot, slow_ms)
        self._segments: deque = deque(maxlen=SEGMENT_RETENTION)

    # region: lifecycle

    async def start(self) -> None:
        self._dir = tempfile.mkdtemp(prefix="wql-dp-")
        self._shards = [_Shard(i) for i in range(self.n_workers)]
        await asyncio.gather(*(self._bring_up(s) for s in self._shards))
        logger.info(
            "delivery plane up: %d sender workers, %d B ring each",
            self.n_workers, self.ring_bytes,
        )

    async def _bring_up(self, shard: _Shard) -> None:
        ring = Ring.create(self.ring_bytes)
        path = os.path.join(self._dir, f"w{shard.idx}-{shard.gen}.sock")
        lsock = socket.socket(socket.AF_UNIX, socket.SOCK_SEQPACKET)
        lsock.bind(path)
        lsock.listen(1)
        lsock.setblocking(False)
        proc = self._ctx.Process(
            target=worker_main,
            args=(shard.idx, path, ring.name,
                  self._failpoints_spec, self._failpoints_seed),
            name=f"wql-delivery-{shard.idx}",
            daemon=True,
        )
        proc.start()
        loop = asyncio.get_running_loop()
        try:
            ctl, _ = await asyncio.wait_for(loop.sock_accept(lsock), 30)
            ctl.setblocking(False)
            ready = json.loads(await asyncio.wait_for(
                loop.sock_recv(ctl, 65536), 30,
            ))
            if ready.get("op") != "ready":
                raise RuntimeError(f"unexpected first packet: {ready}")
        except Exception:
            ring.close()
            ring.unlink()
            if proc.is_alive():
                proc.kill()
            raise
        finally:
            lsock.close()
            try:
                os.unlink(path)
            except OSError:
                pass
        shard.ring, shard.proc, shard.ctl = ring, proc, ctl
        shard.alive = True
        shard.born = time.monotonic()
        shard.stats = {}
        shard.stats_at = shard.born   # freshness clock starts at birth
        # the reader IS the shard's monitor: its EOF-triggered exit path
        # performs eviction + restart, so it does not ride the
        # restart-the-task supervisor
        shard.reader = asyncio.create_task(  # wql: allow(unsupervised-task)
            self._reader(shard), name=f"delivery-reader-{shard.idx}"
        )

    async def stop(self) -> None:
        self._stopping = True
        for shard in self._shards:
            if shard.alive and shard.ctl is not None:
                await self._actl_send(shard, {"op": "stop"})
        for shard in self._shards:
            proc = shard.proc
            if proc is not None:
                await asyncio.to_thread(proc.join, 5)
                if proc.is_alive():
                    logger.warning(
                        "delivery worker %d did not stop — killing",
                        shard.idx,
                    )
                    proc.kill()
                    await asyncio.to_thread(proc.join, 5)
            if shard.reader is not None:
                shard.reader.cancel()
                try:
                    await shard.reader
                except (asyncio.CancelledError, Exception):
                    pass
                shard.reader = None
            self._teardown(shard)
        if self._dir is not None:
            try:
                os.rmdir(self._dir)
            except OSError:
                pass

    def _teardown(self, shard: _Shard) -> None:
        shard.alive = False
        if shard.ctl is not None:
            shard.ctl.close()
            shard.ctl = None
        if shard.ring is not None:
            shard.ring.close()
            shard.ring.unlink()
            shard.ring = None
        shard.proc = None

    # endregion

    # region: control channel

    def _ctl_try(self, shard: _Shard, data: bytes, fds=None) -> str:
        """One non-blocking send attempt: ``ok`` / ``again`` (buffer
        full — worker wedged or slow) / ``err`` (socket dead)."""
        try:
            if fds:
                socket.send_fds(shard.ctl, [data], fds)
            else:
                shard.ctl.send(data)
            return "ok"
        except (BlockingIOError, InterruptedError):
            return "again"
        except OSError:
            return "err"

    def _ctl_send(self, shard: _Shard, msg: dict, fds=None) -> bool:
        """Single-shot control send. Every caller runs on the event
        loop, so this must never wait for the worker: EAGAIN (the
        worker's control buffer is full — it is wedged or far behind)
        counts as failure and the caller's degraded path takes over
        (adopt: the peer stays on the in-process write path; release:
        the worker's end closes when the slot is reused or the worker
        dies). ``_actl_send`` is the retrying variant for coroutines."""
        if shard.ctl is None:
            return False
        return self._ctl_try(shard, json.dumps(msg).encode(), fds) == "ok"

    async def _actl_send(self, shard: _Shard, msg: dict, fds=None) -> bool:
        """Bounded-retry control send for coroutine callers (stop):
        yields to the loop between attempts instead of blocking it."""
        if shard.ctl is None:
            return False
        data = json.dumps(msg).encode()
        deadline = time.monotonic() + 1.0
        while True:
            status = self._ctl_try(shard, data, fds)
            if status != "again" or time.monotonic() >= deadline:
                return status == "ok"
            await asyncio.sleep(0.005)

    async def _reader(self, shard: _Shard) -> None:
        """Drain worker→parent packets; exit means the worker is gone
        (EOF on SIGKILL/crash) and triggers the eviction/restart path."""
        loop = asyncio.get_running_loop()
        try:
            while True:
                data = await loop.sock_recv(shard.ctl, 65536)
                if not data:
                    break
                try:
                    msg = json.loads(data)
                except ValueError:
                    continue
                op = msg.get("op")
                if op == "fail":
                    self._peer_failed(
                        shard, msg.get("slot"),
                        msg.get("reason", "send_failed"),
                    )
                elif op == "stats":
                    self._note_stats(shard, msg)
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass
        except asyncio.CancelledError:
            raise
        if not self._stopping and shard.alive:
            await self._worker_down(shard)

    def _peer_failed(self, shard: _Shard, slot, reason: str) -> None:
        uuid = shard.slots.pop(slot, None)
        if uuid is None:
            return
        shard.peers.pop(uuid, None)
        if self.metrics is not None:
            self.metrics.inc("delivery.peer_failures")
        if self.on_peer_lost is not None:
            self.on_peer_lost(uuid, reason)

    def _note_stats(self, shard: _Shard, msg: dict) -> None:
        prev = shard.stats
        shard.stats_at = time.monotonic()
        if self.metrics is not None:
            for key in ("deliveries", "sends_ok", "send_errors", "bytes"):
                delta = int(msg.get(key, 0)) - int(prev.get(key, 0))
                if delta > 0:
                    self.metrics.inc(f"delivery.{key}", delta)
            if msg.get("drain_ms") and msg.get("drain_ms") != prev.get(
                "drain_ms"
            ):
                self.metrics.observe_ms(
                    "delivery.worker_drain_ms", float(msg["drain_ms"])
                )
            # cumulative worker histograms → registry deltas: the
            # per-worker series (delivery.worker.<i>.e2e_ms) plus the
            # aggregates the SLO reads (delivery.e2e_ms, frame.e2e_ms).
            # Restarted workers re-zero their cumulatives AND their
            # prev packet (stats reset in _bring_up), so merged counts
            # only ever grow — no counter-reset sawtooth in /metrics.
            self._merge_hist(
                msg.get("e2e"), prev.get("e2e"),
                (f"delivery.worker.{shard.idx}.e2e_ms", "delivery.e2e_ms"),
            )
            self._merge_hist(
                msg.get("frame_e2e"), prev.get("frame_e2e"),
                ("frame.e2e_ms",),
            )
        for seg in msg.get("segments", ()):
            try:
                t_write, dwell_ms, write_ms, n_slots, slow_slot, slow_ms = seg
            except (TypeError, ValueError):
                continue
            self._segments.append((
                shard.idx, int(t_write), float(dwell_ms), float(write_ms),
                int(n_slots), int(slow_slot), float(slow_ms),
            ))
        fp = msg.get("fp")
        if fp:
            prev_fp = prev.get("fp") or {}
            deltas = {
                name: int(n) - int(prev_fp.get(name, 0))
                for name, n in fp.items()
            }
            failpoints.registry.note_remote_fires(deltas)
        shard.stats = msg

    def _merge_hist(self, cur, prev, names: tuple) -> None:
        """Diff one cumulative worker histogram against the previous
        packet and merge the delta under every name in ``names``."""
        if not isinstance(cur, dict) or "counts" not in cur:
            return
        prev_counts = (prev or {}).get("counts") or []
        counts = cur["counts"]
        deltas = [
            int(c) - int(prev_counts[i]) if i < len(prev_counts) else int(c)
            for i, c in enumerate(counts)
        ]
        if any(d < 0 for d in deltas):
            # torn/restarted baseline — treat the packet as a fresh
            # start rather than subtracting into negatives
            deltas = [int(c) for c in counts]
            prev = None
        d_total = sum(deltas)
        d_sum = float(cur.get("sum_ms", 0.0)) - float(
            (prev or {}).get("sum_ms", 0.0)
        )
        max_ms = float(cur.get("max_ms", 0.0))
        for name in names:
            self.metrics.merge_histogram(
                name, deltas, d_total, max(d_sum, 0.0), max_ms
            )

    async def _worker_down(self, shard: _Shard) -> None:
        """Crash containment: evict the shard's peers (authoritative
        map stays consistent), reclaim the ring lane, then restart with
        backoff within budget — or retire the shard (degrade)."""
        logger.critical(
            "delivery worker %d died (restarts so far: %d)",
            shard.idx, shard.restarts,
        )
        if self.metrics is not None:
            self.metrics.inc("delivery.worker_deaths")
        # healthy-run refund BEFORE charging this death
        if shard.born and time.monotonic() - shard.born >= HEALTHY_RUN_S:
            shard.restarts = 0
        lost = list(shard.peers)
        shard.peers.clear()
        shard.slots.clear()
        self._teardown(shard)
        if self.on_peer_lost is not None:
            for uuid in lost:
                self.on_peer_lost(uuid, "worker_lost")
        if self._stopping:
            return
        if shard.restarts >= self._budget:
            shard.retired = True
            logger.critical(
                "delivery worker %d exhausted its restart budget (%d) — "
                "shard retired; new peers adopt onto surviving shards "
                "or fall back to the in-process pump", shard.idx,
                self._budget,
            )
            if self.metrics is not None:
                self.metrics.inc("delivery.workers_retired")
            return
        shard.restarts += 1
        shard.gen += 1
        delay = min(self._backoff * (2 ** (shard.restarts - 1)), 30.0)
        logger.warning(
            "restarting delivery worker %d in %.2fs (attempt %d/%d)",
            shard.idx, delay, shard.restarts, self._budget,
        )
        await asyncio.sleep(delay)
        if self._stopping:
            return
        try:
            await self._bring_up(shard)
            logger.info("delivery worker %d restarted", shard.idx)
        except Exception:
            logger.exception(
                "delivery worker %d failed to restart — retrying the "
                "death path", shard.idx,
            )
            await self._worker_down(shard)

    # endregion

    # region: peer adoption / release

    def _pick_shard(self) -> _Shard | None:
        live = [s for s in self._shards if s.alive]
        if not live:
            return None
        return min(live, key=lambda s: len(s.peers))

    def adopt(self, peer, *, fd: int | None = None,
              endpoint: str | None = None) -> bool:
        """Hand a freshly-handshaken peer to a sender worker and rebind
        its write paths onto the owning ring. ``fd`` (WS: the raw TCP
        socket, dup'd by the kernel on passing) XOR ``endpoint`` (ZMQ:
        the connect-back address). False = no live worker (degraded) —
        the caller keeps the peer on the in-process path."""
        shard = self._pick_shard()
        if shard is None:
            return False
        slot = shard.next_slot
        shard.next_slot += 1
        msg = {"op": "add", "slot": slot,
               "kind": "ws" if fd is not None else "zmq"}
        if endpoint is not None:
            msg["endpoint"] = endpoint
        if not self._ctl_send(shard, msg, fds=[fd] if fd is not None else None):
            return False
        shard.peers[peer.uuid] = slot
        shard.slots[slot] = peer.uuid
        peer.shard, peer.slot = shard.idx, slot
        slot_le = struct.pack("<I", slot)

        def try_write(framed, _s=shard, _slot=slot_le):
            return self._submit(_s, framed.payload, _slot)

        def try_write_many(framed_list, _s=shard, _slot=slot_le):
            # not all-or-nothing like a transport buffer: each frame
            # commits independently, so a mid-list ring-full DROPS the
            # remainder (counted) instead of returning False — a
            # False here would make the caller re-send the whole list
            # and duplicate the committed prefix
            for framed in framed_list:
                self._submit(_s, framed.payload, _slot)
            return True

        async def send_raw(data, _s=shard, _slot=slot_le):
            if not await self._asubmit(_s, data, _slot):
                raise ConnectionError(
                    "delivery shard unavailable (worker down or ring "
                    "saturated)"
                )

        peer._try_write = try_write
        peer._try_write_many = try_write_many
        peer._send_raw = send_raw
        if self.metrics is not None:
            self.metrics.inc("delivery.peers_adopted")
        return True

    def release(self, uuid: uuid_mod.UUID) -> None:
        """PeerMap removal hook: the worker closes its end."""
        for shard in self._shards:
            slot = shard.peers.pop(uuid, None)
            if slot is not None:
                shard.slots.pop(slot, None)
                if shard.alive:
                    self._ctl_send(shard, {"op": "remove", "slot": slot})
                return

    # endregion

    # region: frame submission

    def _count_drop(self, n: int = 1) -> None:
        self.ring_drops += n
        if self.metrics is not None:
            self.metrics.inc("delivery.ring_full_drops", n)

    def _submit(self, shard: _Shard, frame, slots_le: bytes,
                t_ingress_ns: int = 0) -> bool:
        """Sync fast path (PeerMap broadcast try_write): bounded spin
        then drop — the event loop must never wedge on a slow shard."""
        if not shard.alive or shard.ring is None:
            return False
        try:
            # chaos site: `error` behaves as an instantly-full ring
            # (caller falls back / drops, counted), `delay` models a
            # congested producer
            failpoints.fire("delivery.ring_write")
        except FailpointError:
            return False
        ring = shard.ring
        if Ring.record_size(len(frame), len(slots_le) // 4) > ring.cap:
            self._count_drop()
            return True  # oversized for any retry — swallow, counted
        if ring.try_write(frame, slots_le, t_ingress_ns):
            self.frames_submitted += 1
            return True
        deadline = time.perf_counter() + SYNC_WAIT_S
        while time.perf_counter() < deadline:
            time.sleep(0.0002)
            if ring.try_write(frame, slots_le, t_ingress_ns):
                self.frames_submitted += 1
                return True
        return False  # caller falls back to the awaited path

    async def _asubmit(self, shard: _Shard, frame, slots_le: bytes,
                       t_ingress_ns: int = 0) -> bool:
        """Async batch path: yields to the loop while the ring drains;
        bounded so a wedged worker degrades (drop + count) instead of
        stalling the tick pipeline."""
        if not shard.alive or shard.ring is None:
            return False
        try:
            await failpoints.afire("delivery.ring_write")
        except FailpointError:
            self._count_drop()
            return False
        ring = shard.ring
        if Ring.record_size(len(frame), len(slots_le) // 4) > ring.cap:
            self._count_drop()
            return True
        if ring.try_write(frame, slots_le, t_ingress_ns):
            self.frames_submitted += 1
            return True
        deadline = time.perf_counter() + ASYNC_WAIT_S
        while time.perf_counter() < deadline:
            await asyncio.sleep(0.001)
            if not shard.alive or shard.ring is not ring:
                # the worker died (or restarted onto a fresh ring)
                # while we waited — the captured ring is torn down
                return False
            if ring.try_write(frame, slots_le, t_ingress_ns):
                self.frames_submitted += 1
                return True
        self._count_drop()
        return False

    async def deliver(
        self, groups: dict[int, tuple[bytes, array]],
        t_ingress_ns: int = 0,
    ) -> int:
        """One message's fan-out: ``{shard_idx: (frame, slot_array)}``
        — the frame is written ONCE per shard regardless of the slot
        count (the serialize-once discipline extended across the
        process boundary). ``t_ingress_ns`` is the frame clock the
        owning worker closes at socket-write-complete. Returns sends
        attempted."""
        n = 0
        for shard_idx, (frame, slots) in groups.items():
            shard = self._shards[shard_idx]
            n += len(slots)
            if not await self._asubmit(
                shard, frame, slots.tobytes(), t_ingress_ns
            ):
                self._count_drop(len(slots))
                if self.on_frame_drop is not None:
                    for slot in slots:
                        u = shard.slots.get(slot)
                        if u is not None:
                            self.on_frame_drop(u)
        return n

    # endregion

    # region: introspection

    def stats_age_s(self, idx: int) -> float | None:
        """Seconds since the worker's last stats push (None when the
        shard is down/retired — deadness is its own signal)."""
        shard = self._shards[idx] if idx < len(self._shards) else None
        if shard is None or not shard.alive:
            return None
        return max(0.0, time.monotonic() - shard.stats_at)

    def _stale_workers(self) -> int:
        """Alive-but-silent workers: the control-channel stats push
        stopped for > STATS_STALE_INTERVALS intervals. A wedged drain
        loop (e.g. a multi-second blocking send) looks exactly like
        this — alive process, no progress."""
        horizon = STATS_STALE_INTERVALS * STATS_INTERVAL
        return sum(
            1 for s in self._shards
            if s.alive and time.monotonic() - s.stats_at > horizon
        )

    def degraded(self) -> bool:
        return (
            any(s.retired or not s.alive for s in self._shards)
            or self._stale_workers() > 0
        )

    def alive_workers(self) -> int:
        return sum(1 for s in self._shards if s.alive)

    def stats(self) -> dict:
        return {
            "workers": self.n_workers,
            "alive": self.alive_workers(),
            "retired": sum(1 for s in self._shards if s.retired),
            "restarts": sum(s.restarts for s in self._shards),
            "peers": sum(len(s.peers) for s in self._shards),
            "frames_submitted": self.frames_submitted,
            "ring_full_drops": self.ring_drops,
            "stats_stale": self._stale_workers(),
        }

    def worker_stats(self, idx: int) -> dict:
        """Per-worker numeric leaves for the ``delivery.worker.<i>``
        gauge (flattened into /metrics by render_prometheus)."""
        shard = self._shards[idx] if idx < len(self._shards) else None
        if shard is None:
            return {}
        age = self.stats_age_s(idx)
        out = {
            "alive": int(shard.alive),
            "retired": int(shard.retired),
            "restarts": shard.restarts,
            "peers": len(shard.peers),
            "ring_pending_bytes": (
                shard.ring.pending_bytes()
                if shard.alive and shard.ring is not None else 0
            ),
            "stats_age_s": round(age, 3) if age is not None else -1.0,
        }
        for key in ("records", "deliveries", "sends_ok", "send_errors",
                    "bytes", "evictions"):
            if key in shard.stats:
                out[key] = int(shard.stats[key])
        return out

    def stitch(self, trace) -> list[dict]:
        """Flight-recorder stitcher: synthesize ``delivery.worker_flush``
        child spans under a tick trace's ``tick.deliver`` from the
        worker-reported segments whose ring-write stamp falls inside
        the deliver window. Ring-write stamps are CLOCK_MONOTONIC ns
        and trace span clocks are ``perf_counter`` seconds — the same
        clock on Linux, so the windows align without translation (on a
        platform where they differ, segments simply fail to match and
        the trace degrades to parent-side spans only)."""
        with trace._lock:
            deliver = [s for s in trace.spans if s.name == "tick.deliver"]
        if not deliver or not self._segments:
            return []
        out: list[dict] = []
        base = trace.perf_start
        for ds in deliver:
            w0 = ds.t0 - 1e-4
            w1 = ds.t0 + ds.dur_ms / 1e3 + 1e-4
            for (worker, t_write, dwell_ms, write_ms, n_slots,
                 slow_slot, slow_ms) in self._segments:
                t_write_s = t_write / 1e9
                if not (w0 <= t_write_s <= w1):
                    continue
                tags = {
                    "worker": worker,
                    "ring_dwell_ms": dwell_ms,
                    "write_ms": write_ms,
                    "slots": n_slots,
                }
                if slow_slot >= 0:
                    tags["slowest_slot"] = slow_slot
                    tags["slowest_send_ms"] = slow_ms
                out.append({
                    # negative ids: synthetic spans can never collide
                    # with the trace's own monotonically-positive ids
                    "id": -(len(out) + 1),
                    "parent": ds.id,
                    "name": "delivery.worker_flush",
                    "t0_ms": round((t_write_s - base) * 1e3, 3),
                    "dur_ms": round(dwell_ms + write_ms, 3),
                    "tags": tags,
                    "thread": f"delivery-worker-{worker}",
                })
        return out

    # endregion
