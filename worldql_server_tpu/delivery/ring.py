"""Shared-memory SPSC fan-out ring: the parent→worker frame conduit.

One ring per sender worker (single producer: the parent's frame pump;
single consumer: that worker), over one ``multiprocessing.
shared_memory`` block. Records are raw struct frames —

    [u32 kind][u32 frame_len][u32 n_slots]
    [u64 t_ingress_ns][u64 t_ring_write_ns]
    [frame bytes][n_slots × u32 slot ids]   (8-byte aligned)

The two stamps are CLOCK_MONOTONIC nanoseconds (``time.monotonic_ns``
— the same clock domain as ``time.perf_counter`` on Linux, so worker-
side completions stitch directly into parent-side span traces):
``t_ingress_ns`` is the frame clock opened at router dispatch / ticker
flush start (0 = not frame-clocked, e.g. broadcasts), and
``t_ring_write_ns`` is stamped by :meth:`Ring.try_write` itself — the
moment the frame entered the delivery plane. Workers subtract both
from their socket-write-complete time for the e2e histograms.

— written in place with ``pack_into``/buffer slicing: there is no
pickling and no intermediate frame copy on the write path (enforced by
the ``worker-unsafe-delivery`` lint rule). Cursors are MONOTONIC u64
byte counts in the block header (``head`` written only by the producer,
``tail`` only by the consumer), so the SPSC pair needs no lock: on
x86/ARM the interpreter's stores land in program order and each side
reads the other's cursor before touching data it guards. A record that
would straddle the block end burns the remainder with a WRAP marker
(or, when even a record header doesn't fit, the bare remainder — the
consumer mirrors the same arithmetic).

``try_write`` never blocks: a full ring returns False and the caller
owns the wait-or-drop policy (plane.py bounds the wait so a wedged
worker can never stall the tick pipeline).
"""

from __future__ import annotations

import struct
import time
from multiprocessing import shared_memory

#: header layout: head u64 @0 (producer), tail u64 @8 (consumer),
#: capacity u64 @16 (set once at create; SharedMemory rounds the block
#: to page size so the true cap must ride in-band)
_HDR = 64
_REC = struct.Struct("<IIIQQ")
_CUR = struct.Struct("<Q")

KIND_FRAME = 1
KIND_WRAP = 2

#: floor on a configured ring size — below this a single max-size
#: control batch could never fit and the writer would spin forever
RING_MIN_BYTES = 64 * 1024


def _pow2(n: int) -> int:
    return 1 << (max(int(n), 1) - 1).bit_length()


class Ring:
    """SPSC byte ring over one shared-memory block. The parent calls
    :meth:`create` and writes; the worker calls :meth:`attach` (by
    name) and reads. Either side may close(); only the creator
    unlinks."""

    def __init__(self, shm: shared_memory.SharedMemory, cap: int):
        self.shm = shm
        self.buf = shm.buf
        self.cap = cap

    # region: lifecycle

    @classmethod
    def create(cls, capacity_bytes: int) -> "Ring":
        cap = _pow2(max(capacity_bytes, RING_MIN_BYTES))
        shm = shared_memory.SharedMemory(create=True, size=_HDR + cap)
        shm.buf[:_HDR] = b"\x00" * _HDR
        _CUR.pack_into(shm.buf, 16, cap)
        return cls(shm, cap)

    @classmethod
    def attach(cls, name: str) -> "Ring":
        shm = shared_memory.SharedMemory(name=name)
        # De-register from the ATTACHING process's resource tracker:
        # the creator owns the block's lifetime (unlink), and on
        # Python < 3.13 an attach silently registers too — so a dying
        # attacher's tracker would unlink a ring its peers still use
        # (cluster shards re-attach the same rings across restarts).
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:
            pass  # tracker internals shifted — worst case, a warning
        cap = _CUR.unpack_from(shm.buf, 16)[0]
        return cls(shm, int(cap))

    @property
    def name(self) -> str:
        return self.shm.name

    def close(self) -> None:
        self.buf = None  # release the exported memoryview first
        self.shm.close()

    def unlink(self) -> None:
        try:
            self.shm.unlink()
        except FileNotFoundError:
            pass

    # endregion

    # region: cursors

    def _head(self) -> int:
        return _CUR.unpack_from(self.buf, 0)[0]

    def _tail(self) -> int:
        return _CUR.unpack_from(self.buf, 8)[0]

    def pending_bytes(self) -> int:
        return self._head() - self._tail()

    # endregion

    @staticmethod
    def record_size(frame_len: int, n_slots: int) -> int:
        return (_REC.size + frame_len + 4 * n_slots + 7) & ~7

    def try_write(self, frame, slots_le: bytes, t_ingress_ns: int = 0) -> bool:
        """Append one delivery record (``slots_le`` is the target slot
        ids already packed little-endian u32, e.g. ``array('I')``
        bytes). ``t_ingress_ns`` is the frame clock opened at router
        dispatch / ticker flush start (0 = unclocked); the ring-write
        stamp is taken here. False when the ring lacks space — the
        caller decides whether to wait, drop, or spill."""
        n_slots = len(slots_le) // 4
        size = self.record_size(len(frame), n_slots)
        head, tail = self._head(), self._tail()
        free = self.cap - (head - tail)
        pos = head % self.cap
        rem = self.cap - pos
        if rem < size:
            # wrap: the record must be contiguous, so the remainder is
            # burned (marked when a header fits; the consumer derives
            # the skip either way)
            if free < rem + size:
                return False
            if rem >= _REC.size:
                _REC.pack_into(self.buf, _HDR + pos, KIND_WRAP, 0, 0, 0, 0)
            head += rem
            pos = 0
        elif free < size:
            return False
        off = _HDR + pos
        _REC.pack_into(
            self.buf, off, KIND_FRAME, len(frame), n_slots,
            t_ingress_ns, time.monotonic_ns(),
        )
        off += _REC.size
        self.buf[off:off + len(frame)] = frame
        off += len(frame)
        self.buf[off:off + len(slots_le)] = slots_le
        # publish LAST: the consumer sees the cursor only after the
        # record bytes are in place (x86/ARM store order + the
        # interpreter's per-bytecode sequencing)
        _CUR.pack_into(self.buf, 0, head + size)
        return True

    def read(self):
        """Consume one record → ``(frame_bytes, slot_ids: list[int])``
        or None when the ring is empty (timestamp-free compatibility
        surface; see :meth:`read_record`)."""
        rec = self.read_record()
        return None if rec is None else rec[:2]

    def read_record(self):
        """Consume one record → ``(frame_bytes, slot_ids, t_ingress_ns,
        t_ring_write_ns)`` or None when the ring is empty. The frame is
        COPIED out of the block before the tail advances — the consumer
        may buffer it past the slot's reuse."""
        while True:
            head, tail = self._head(), self._tail()
            if tail >= head:
                return None
            pos = tail % self.cap
            rem = self.cap - pos
            if rem < _REC.size:
                _CUR.pack_into(self.buf, 8, tail + rem)
                continue
            kind, frame_len, n_slots, t_ingress, t_write = _REC.unpack_from(
                self.buf, _HDR + pos
            )
            if kind == KIND_WRAP:
                _CUR.pack_into(self.buf, 8, tail + rem)
                continue
            size = self.record_size(frame_len, n_slots)
            off = _HDR + pos + _REC.size
            frame = bytes(self.buf[off:off + frame_len])
            off += frame_len
            slots = list(
                struct.unpack_from(f"<{n_slots}I", self.buf, off)
            ) if n_slots else []
            _CUR.pack_into(self.buf, 8, tail + size)
            return frame, slots, t_ingress, t_write
