"""Multi-core delivery plane (ISSUE 6).

Breaks the GIL ceiling on fan-out delivery: after the ticker's collect
stage, the serialize-once frame pump writes ``(frame_bytes, slot_list)``
batches into per-worker shared-memory rings (:mod:`.ring` — struct
framing, no per-frame pickling), drained by N sender worker processes
(:mod:`.worker`) that own disjoint shards of the live sockets. The
parent keeps authoritative PeerMap membership (:mod:`.plane`) and
routes each delivery batch to the owning shard; workers report
send-failures/evictions back over a control channel so staleness
sweeping and ``on_peer_removed`` semantics are unchanged.

``--delivery-workers 0`` (the default) constructs none of this and the
sequential in-process pump stays byte-for-byte.
"""

from .plane import DeliveryPlane
from .ring import Ring, RING_MIN_BYTES

__all__ = ["DeliveryPlane", "Ring", "RING_MIN_BYTES"]
