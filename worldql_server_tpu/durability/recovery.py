"""Boot-time WAL scan + replay.

Crash model: the server dies at an arbitrary instant. The only
in-flight write is the tail of the NEWEST segment (segments are sealed
before rotation, and the group-commit worker is the single writer), so
recovery must tolerate exactly one torn entry: a frame whose header,
payload, or CRC is incomplete at end-of-log. Everything before it was
fsynced and acked; everything after it was never acked to any client.

Replay leans on the store's append-with-dedupe-on-read contract
(storage/store.py): re-applying an entry that already reached the
store before the crash just appends a duplicate row that the next read
collapses — so recovery needs no exactly-once bookkeeping, only
prefix-ordered replay. Deletes are naturally idempotent.

After a successful replay the replayed segments are purged (the store
committed every batch), bounding both WAL disk usage and the NEXT
recovery's work — the same role the periodic checkpoint plays while
serving.
"""

from __future__ import annotations

import logging
import os
import zlib
from dataclasses import dataclass, field
from typing import Iterator

from ..robustness import failpoints
from .wal import (
    HEADER,
    MAGIC,
    MAX_ENTRY_BYTES,
    WalCorruption,
    decode_entry,
    list_segments,
)

logger = logging.getLogger(__name__)


@dataclass
class RecoveryStats:
    segments: int = 0
    entries: int = 0
    records: int = 0
    torn_entries: int = 0
    torn_bytes: int = 0
    purged_segments: int = 0
    errors: list[str] = field(default_factory=list)

    def as_dict(self) -> dict:
        return {
            "segments": self.segments,
            "entries": self.entries,
            "records": self.records,
            "torn_entries": self.torn_entries,
            "torn_bytes": self.torn_bytes,
            "purged_segments": self.purged_segments,
            "errors": list(self.errors),
        }


def iter_segment_entries(path: str) -> Iterator[tuple[int, bytes]]:
    """Yield ``(entry_start_offset, payload)`` for every COMPLETE entry
    in one segment; raises :class:`WalCorruption` (carrying the torn
    offset in ``args[1]``) at the first incomplete/invalid frame."""
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise WalCorruption(
                f"bad segment magic in {path!r}", 0
            )
        offset = len(MAGIC)
        while True:
            header = f.read(HEADER.size)
            if not header:
                return  # clean end of segment
            if len(header) < HEADER.size:
                raise WalCorruption("torn entry header", offset)
            length, crc = HEADER.unpack(header)
            if length > MAX_ENTRY_BYTES:
                raise WalCorruption(
                    f"implausible entry length {length}", offset
                )
            payload = f.read(length)
            if len(payload) < length:
                raise WalCorruption("torn entry payload", offset)
            if zlib.crc32(payload) != crc:
                raise WalCorruption("entry CRC mismatch", offset)
            yield offset, payload
            offset += HEADER.size + length


def scan_wal(wal_dir: str) -> tuple[list[tuple[str, list]], RecoveryStats]:
    """Scan every segment in order → (ops, stats). ``ops`` is the
    replayable prefix: ``("insert"|"delete", records)`` tuples.

    A bad frame in the NEWEST segment is the expected torn tail: scan
    stops there. A bad frame in an older (sealed) segment means real
    corruption — that segment's remaining entries are skipped with a
    loud error, but later segments still replay: every entry is
    self-contained, inserts are append-with-dedupe, and serving from a
    partially-recovered store beats refusing to boot."""
    stats = RecoveryStats()
    ops: list[tuple[str, list]] = []
    segments = list_segments(wal_dir)
    stats.segments = len(segments)
    for i, (seq, path) in enumerate(segments):
        is_last = i == len(segments) - 1
        try:
            for offset, payload in iter_segment_entries(path):
                try:
                    op, records = decode_entry(payload)
                except WalCorruption as exc:
                    raise WalCorruption(exc.args[0], offset) from exc
                except Exception as exc:
                    # CRC-valid but undecodable (codec drift, e.g. a
                    # version change): same policy as bit rot — keep
                    # the decoded prefix, keep booting. Must never
                    # escape scan_wal and abort recovery.
                    raise WalCorruption(
                        f"entry decode failed: {exc!r}", offset
                    ) from exc
                ops.append((op, records))
                stats.entries += 1
                stats.records += len(records)
        except WalCorruption as exc:
            torn_at = exc.args[1] if len(exc.args) > 1 else 0
            stats.torn_entries += 1
            stats.torn_bytes += max(os.path.getsize(path) - torn_at, 0)
            if is_last:
                logger.warning(
                    "WAL %s: torn tail at byte %d (%s) — replaying the "
                    "acked prefix", path, torn_at, exc.args[0],
                )
            else:
                msg = (
                    f"WAL {path}: corruption at byte {torn_at} in a "
                    f"SEALED segment ({exc.args[0]}) — its remaining "
                    "entries are lost"
                )
                stats.errors.append(msg)
                logger.error(msg)
    return ops, stats


async def recover(
    store, wal_dir: str, *, purge: bool = True, metrics=None
) -> RecoveryStats:
    """Replay the WAL into ``store`` (which must be initialized).
    With ``purge`` (default), fully-replayed segments are deleted —
    every batch was committed by the store call, so the log's job is
    done. Store errors during replay leave the WAL intact for the next
    attempt and are recorded in ``stats.errors``."""
    ops, stats = scan_wal(wal_dir)
    failed = False
    for op, records in ops:
        try:
            # chaos seam: lets the scenario suite stretch or fail the
            # boot-time replay deterministically (a reconnect storm
            # landing mid-replay needs recovery to take a while)
            await failpoints.afire("recovery.apply")
            if op == "insert":
                await store.insert_records(records)
            else:
                await store.delete_records(records)
        except Exception as exc:
            failed = True
            msg = f"WAL replay {op} of {len(records)} records failed: {exc}"
            stats.errors.append(msg)
            logger.exception(msg)
            break  # keep ordering: don't apply past a failed batch
    if purge and not failed:
        for _seq, path in list_segments(wal_dir):
            try:
                os.unlink(path)
                stats.purged_segments += 1
            except OSError:
                logger.exception("could not purge WAL segment %s", path)
    if metrics is not None:
        metrics.inc("durability.recovered_entries", stats.entries)
        metrics.inc("durability.recovered_records", stats.records)
        metrics.inc("durability.recovery_torn_entries", stats.torn_entries)
    if stats.entries or stats.torn_entries:
        logger.info(
            "WAL recovery: %d entries (%d records) replayed from %d "
            "segments, %d torn, %d purged",
            stats.entries, stats.records, stats.segments,
            stats.torn_entries, stats.purged_segments,
        )
    return stats
