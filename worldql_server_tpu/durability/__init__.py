"""Durability engine: WAL + write-behind pipeline + crash recovery.

The reference persists every record op synchronously inside the message
handler — one DB round-trip per RecordCreate on the very event loop the
ticker and transports share (SURVEY §3, processing/record_create.rs).
This package takes record persistence off that hot path the same way
the spatial index batches device mutations into ticks:

* :mod:`.wal` — segmented append-only write-ahead log, length+CRC32
  framed entries (payload = the codec's Record serialization), group
  commit on a worker thread that coalesces fsyncs.
* :mod:`.pipeline` — write-behind applier: a bounded queue drains
  insert/delete/dedupe ops into ``executemany``-sized store batches off
  the event loop, applies backpressure when full, and gives region
  reads read-your-writes by waiting out pending ops for the queried
  region.
* :mod:`.recovery` — boot-time WAL scan + replay tolerating a torn
  tail, leaning on the store's append-with-dedupe-on-read contract so
  re-replaying an already-applied entry is harmless.

Three durability modes (engine/config.py ``durability=``):

* ``off`` — reference-equivalent: handlers await the store directly,
  no WAL, byte-for-byte identical wire behavior.
* ``wal`` — handlers return after the WAL group-commit fsync ack +
  enqueue; the store commit happens behind the handler.
* ``sync`` — WAL append with immediate fsync AND a synchronous store
  commit before the handler returns (strongest, slowest).
"""

from .pipeline import DurabilityPipeline
from .recovery import RecoveryStats, recover, scan_wal
from .wal import (
    WalCorruption,
    WriteAheadLog,
    decode_entry,
    encode_delete,
    encode_insert,
)

__all__ = [
    "DurabilityPipeline",
    "RecoveryStats",
    "WalCorruption",
    "WriteAheadLog",
    "decode_entry",
    "encode_delete",
    "encode_insert",
    "recover",
    "scan_wal",
]
