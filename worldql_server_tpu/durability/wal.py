"""Segmented append-only write-ahead log with group commit.

File format — designed so recovery can decide "complete entry or torn
tail" from local information only:

* Each segment starts with the 8-byte magic ``WQLWAL01``.
* Each entry is ``[u32 payload length][u32 crc32(payload)][payload]``
  (little-endian). The payload is the wire codec's serialization of a
  ``Message`` whose instruction carries the op (RecordCreate = insert,
  RecordDelete = delete) and whose ``records`` carry the data — the
  exact bytes the record arrived in, so the WAL needs no second
  serializer and inherits the codec's fuzz/sanitizer coverage.
* Segments are ``wal-<seq>.log``; a segment is sealed (never written
  again) once its size crosses ``segment_bytes`` and a new one opens.

Group commit: appends from the event loop enqueue framed entries to a
dedicated writer thread and await a future. The thread drains the
queue into ONE write+fsync and resolves all of their futures — so
appends that arrive while a sync is in flight coalesce naturally, and
a burst of record traffic costs one disk sync, not one per message.
The handler's latency is "enqueue + group fsync", never a store
commit. ``fsync_ms > 0`` additionally holds each batch open that long
after its first entry, trading per-append latency for even fewer
syncs under sustained load (Postgres ``commit_delay`` semantics); the
default is 0.

Checkpoint/close run through the same queue, so they serialize with
writes without any file-level locking.
"""

from __future__ import annotations

import asyncio
import logging
import os
import queue
import re
import struct
import threading
import time
import zlib

from ..observability.spans import NOOP_SPAN
from ..protocol.codec import deserialize_message, serialize_message
from ..protocol.types import Instruction, Message, Record
from ..robustness import failpoints

logger = logging.getLogger(__name__)

MAGIC = b"WQLWAL01"
HEADER = struct.Struct("<II")  # payload length, crc32(payload)
_SEGMENT_RE = re.compile(r"^wal-(\d{8})\.log$")

#: hard ceiling on one WAL entry — matches the transports' inbound
#: frame cap order of magnitude; a larger length field is corruption,
#: not a big entry (recovery uses this to reject garbage lengths
#: without allocating them)
MAX_ENTRY_BYTES = 64 * 1024 * 1024


class WalCorruption(Exception):
    """A WAL entry failed its length/CRC frame check."""


def segment_name(seq: int) -> str:
    return f"wal-{seq:08d}.log"


def list_segments(wal_dir: str) -> list[tuple[int, str]]:
    """Sorted (seq, path) for every segment file in ``wal_dir``."""
    try:
        names = os.listdir(wal_dir)
    except FileNotFoundError:
        return []
    out = []
    for name in names:
        m = _SEGMENT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(wal_dir, name)))
    out.sort()
    return out


# region: entry codec (reuses the wire codec's Record serialization)


def encode_insert(records: list[Record]) -> bytes:
    return serialize_message(
        Message(instruction=Instruction.RECORD_CREATE, records=list(records))
    )


def encode_delete(records: list[Record]) -> bytes:
    return serialize_message(
        Message(instruction=Instruction.RECORD_DELETE, records=list(records))
    )


def decode_entry(payload: bytes) -> tuple[str, list[Record]]:
    """Payload bytes → ``("insert"|"delete", records)``; raises
    :class:`WalCorruption` on anything else (a CRC-valid entry with an
    unknown instruction means a version mismatch, not bit rot — fail
    loudly either way)."""
    msg = deserialize_message(payload)
    if msg.instruction == Instruction.RECORD_CREATE:
        return "insert", msg.records
    if msg.instruction == Instruction.RECORD_DELETE:
        return "delete", msg.records
    raise WalCorruption(
        f"WAL entry carries non-record instruction {msg.instruction!r}"
    )


def frame_entry(payload: bytes) -> bytes:
    return HEADER.pack(len(payload), zlib.crc32(payload)) + payload


# endregion


class WriteAheadLog:
    """Append-only segmented log owned by one writer thread."""

    def __init__(
        self,
        wal_dir: str,
        *,
        fsync_ms: float = 0.0,
        segment_bytes: int = 64 * 1024 * 1024,
        metrics=None,
        tracer=None,
    ):
        self.dir = wal_dir
        self._fsync_s = max(fsync_ms, 0.0) / 1e3
        self._segment_bytes = segment_bytes
        self._metrics = metrics
        # observability.Tracer: the writer thread emits a loose
        # "wal.fsync" span per group commit (Trace.add is lock-guarded,
        # so recording from this thread is safe)
        self._tracer = tracer
        self._q: queue.Queue = queue.Queue()
        self._thread: threading.Thread | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._file = None
        self._seq = 0
        self._size = 0
        # stats mirrors updated by the worker, read from the loop —
        # plain attributes are fine under the GIL (single writer)
        self.appended_entries = 0
        self.fsyncs = 0

    # region: lifecycle

    def start(self) -> None:
        """Open the next segment and spawn the writer thread. Must run
        on the event loop (appends resolve their futures back onto
        it). Recovery must already have drained/purged old segments —
        the WAL never appends to a pre-existing file."""
        assert self._thread is None, "WAL already started"
        self._loop = asyncio.get_running_loop()
        os.makedirs(self.dir, exist_ok=True)
        existing = list_segments(self.dir)
        # single-writer handoff: this loop-side write (and the
        # _open_segment below) happens strictly BEFORE the writer
        # thread spawns; Thread.start() publishes it, and from then on
        # only the worker touches _seq/_file/_size
        self._seq = existing[-1][0] + 1 if existing else 0  # wql: allow(unlocked-shared-write)
        self._open_segment()
        self._thread = threading.Thread(
            target=self._worker, name="wal-writer", daemon=True
        )
        self._thread.start()

    async def append(self, payload: bytes) -> None:
        """Durably append one entry: returns once the entry is written
        AND fsynced (possibly sharing its fsync with a whole group)."""
        # an armed `wal.append` error rejects the append before it is
        # framed — the pipeline's enqueue-first ordering means the op
        # still reaches the store while the handler reports the failure
        await failpoints.afire("wal.append")
        fut = self._loop.create_future()
        self._q.put(("write", frame_entry(payload), fut))
        await fut

    async def rotate(self) -> int:
        """Seal the current segment (flush + fsync + close) and open a
        fresh one; returns the sealed segment's seq. New appends land
        strictly past the returned boundary — the first half of a
        checkpoint: rotate, THEN drain the pipeline, THEN
        :meth:`purge_upto` the boundary, so a handler mid-append can
        never slip an entry into a segment the checkpoint purges."""
        if self._thread is None:
            return -1  # never started (failed boot): nothing to seal
        fut = self._loop.create_future()
        self._q.put(("rotate", None, fut))
        return await fut

    async def purge_upto(self, boundary: int) -> int:
        """Delete every sealed segment with seq <= ``boundary``. Only
        call once every entry in those segments has provably reached
        the store: a completed pipeline drain AFTER the :meth:`rotate`
        that returned ``boundary``. Returns segments deleted."""
        if self._thread is None or boundary < 0:
            return 0
        fut = self._loop.create_future()
        self._q.put(("purge", boundary, fut))
        return await fut

    async def checkpoint(self) -> int:
        """Seal the current segment and delete every older one — the
        SHUTDOWN-time truncation: only safe when no concurrent append
        can arrive (transports stopped, applier drained); while serving
        use rotate → drain → purge_upto instead. Returns the number of
        segments deleted."""
        if self._thread is None:
            return 0  # never started (failed boot): nothing to truncate
        fut = self._loop.create_future()
        self._q.put(("checkpoint", None, fut))
        return await fut

    async def close(self) -> None:
        if self._thread is None:
            return
        fut = self._loop.create_future()
        self._q.put(("stop", None, fut))
        await fut
        self._thread.join(timeout=10)
        self._thread = None

    def stats(self) -> dict:
        return {
            "wal_segments": len(list_segments(self.dir)),
            "wal_segment_seq": self._seq,
            "wal_appends": self.appended_entries,
            "wal_fsyncs": self.fsyncs,
        }

    # endregion

    # region: writer thread

    def _open_segment(self) -> None:
        # reached from both domains but never concurrently: once from
        # start() before the thread exists (happens-before via
        # Thread.start()), afterwards only from the worker's _rotate
        path = os.path.join(self.dir, segment_name(self._seq))
        self._file = open(path, "ab")  # wql: allow(unlocked-shared-write)
        if self._file.tell() == 0:
            self._file.write(MAGIC)
            self._file.flush()
        self._size = self._file.tell()  # wql: allow(unlocked-shared-write)

    def _rotate(self) -> None:
        self._file.flush()
        os.fsync(self._file.fileno())
        self._file.close()
        # worker-thread only (see _open_segment ownership note)
        self._seq += 1  # wql: allow(unlocked-shared-write)
        self._open_segment()

    def _write_frame(self, frame: bytes) -> None:
        if self._size + len(frame) > self._segment_bytes and self._size > len(MAGIC):
            self._rotate()
        self._file.write(frame)
        # worker-thread only (see _open_segment ownership note)
        self._size += len(frame)  # wql: allow(unlocked-shared-write)

    def _worker(self) -> None:
        while True:
            batch = [self._q.get()]
            if batch[0][0] == "write":
                # group-commit window: coalesce every append that lands
                # within fsync_ms of the first into one write+fsync
                deadline = time.monotonic() + self._fsync_s
                while batch[-1][0] == "write":
                    timeout = deadline - time.monotonic()
                    try:
                        if timeout > 0:
                            batch.append(self._q.get(timeout=timeout))
                        else:
                            batch.append(self._q.get_nowait())
                    except queue.Empty:
                        break
            stop = self._process_batch(batch)
            if stop:
                return

    def _process_batch(self, batch: list) -> bool:
        writes = [(frame, fut) for op, frame, fut in batch if op == "write"]
        controls = [(op, arg, fut) for op, arg, fut in batch if op != "write"]

        if writes:
            t0 = time.perf_counter()
            span = (
                self._tracer.span("wal.fsync", group=len(writes))
                if self._tracer is not None and self._tracer.enabled
                else NOOP_SPAN
            )
            with span:
                try:
                    # `wal.fsync` failpoint: error = the whole group
                    # fails before any byte lands (clean disk-full
                    # simulation); delay = fsync latency, blocking only
                    # this writer thread (group commit absorbs it)
                    failpoints.fire("wal.fsync")
                    for frame, _ in writes:
                        self._write_frame(frame)
                    self._file.flush()
                    os.fsync(self._file.fileno())
                except Exception as exc:  # disk full / IO error
                    logger.exception("WAL write/fsync failed")
                    self._resolve([fut for _, fut in writes], exc)
                else:
                    self.fsyncs += 1
                    self.appended_entries += len(writes)
                    fsync_ms = (time.perf_counter() - t0) * 1e3
                    self._resolve(
                        [fut for _, fut in writes], None, fsync_ms,
                        len(writes),
                    )

        for op, arg, fut in controls:
            if op == "rotate":
                try:
                    self._rotate()
                    self._resolve([fut], None, result=self._seq - 1)
                except Exception as exc:
                    logger.exception("WAL rotate failed")
                    self._resolve([fut], exc)
            elif op == "purge":
                try:
                    purged = 0
                    for seq, path in list_segments(self.dir):
                        if seq <= arg and seq < self._seq:
                            os.unlink(path)
                            purged += 1
                    self._resolve([fut], None, result=purged)
                except Exception as exc:
                    logger.exception("WAL purge failed")
                    self._resolve([fut], exc)
            elif op == "checkpoint":
                try:
                    self._rotate()
                    purged = 0
                    for seq, path in list_segments(self.dir):
                        if seq < self._seq:
                            os.unlink(path)
                            purged += 1
                    self._resolve([fut], None, result=purged)
                except Exception as exc:
                    logger.exception("WAL checkpoint failed")
                    self._resolve([fut], exc)
            elif op == "stop":
                try:
                    self._file.flush()
                    os.fsync(self._file.fileno())
                    self._file.close()
                except Exception:
                    logger.exception("WAL close failed")
                self._resolve([fut], None)
                return True
        return False

    def _resolve(self, futs, exc, fsync_ms=None, n_writes=0, result=None):
        """Resolve futures (and report metrics) back on the event loop —
        the Metrics registry is loop-confined by design."""

        def deliver():
            if fsync_ms is not None and self._metrics is not None:
                self._metrics.observe_ms("durability.fsync_ms", fsync_ms)
                self._metrics.inc("durability.wal_appends", n_writes)
            for fut in futs:
                if fut.done():
                    continue
                if exc is not None:
                    fut.set_exception(exc)
                else:
                    fut.set_result(result)

        try:
            self._loop.call_soon_threadsafe(deliver)
        except RuntimeError:
            # loop already closed mid-shutdown: nothing to deliver to
            pass
