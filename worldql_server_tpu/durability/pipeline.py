"""Write-behind record persistence pipeline (the durability frontend).

One object answers every record op the router used to await on the
store directly, in one of three modes:

* ``off`` — pass-through: every call awaits the store inline, exactly
  the pre-durability behavior (reference semantics, byte-for-byte).
* ``sync`` — WAL first (immediate fsync), then the store inline.
* ``wal`` — WAL group-commit ack, then enqueue onto a BOUNDED queue; a
  background applier drains ops into ``executemany``-sized store
  batches off the handler path. A full queue backpressures the
  handler (``await queue.put``), which in turn backpressures the
  transport read loop — memory stays bounded under any burst.

Read-your-writes: region reads in ``wal`` mode first wait out every
pending op that touches the queried DB region (a per-region high-water
sequence map; ops that can't be keyed conservatively mark ALL regions).
Reads of untouched regions never wait.

Dedupe (read-repair) ops ride the queue but are NOT WAL-logged: they
are derivable — any lost dedupe is redone by the next read of that
region, per the store's append-with-dedupe-on-read contract.

Ordering invariant: in ``wal`` mode an op is ENQUEUED (sequence
stamped, region map updated) before its WAL append is awaited. Any
entry that reaches the log therefore belongs to an already-sequenced
op, so a checkpoint that rotates the WAL and then drains provably
covers every entry in the segments it purges — there is no
append→enqueue window for a truncation to slip through.

Failed batches: a store error drops the batch from the queue (barriers
must never deadlock on a wedged store) but bumps ``dropped_batches``,
which the server reads to SKIP WAL truncation — both the periodic
checkpoint and shutdown keep every segment, so the dropped entries are
re-applied by boot-time replay. Replay re-runs the whole retained
prefix in WAL order, so already-applied neighbors are harmless
(append-with-dedupe-on-read; deletes are idempotent).
"""

from __future__ import annotations

import asyncio
import logging

from ..observability.spans import NOOP_SPAN
from ..robustness import failpoints
from ..spatial.quantize import region_coords
from ..storage.store import DedupeOp, RecordStore, StoredRecord
from ..protocol.types import Record, Vector3
from .wal import WriteAheadLog, encode_delete, encode_insert

logger = logging.getLogger(__name__)

#: conservative region key for ops whose position can't be quantized
#: (hostile NaN coords): every subsequent read waits for them
_ALL_REGIONS = ("*",)

MODES = ("off", "wal", "sync")


class DurabilityPipeline:
    def __init__(
        self,
        store: RecordStore,
        *,
        mode: str = "off",
        wal: WriteAheadLog | None = None,
        config=None,
        metrics=None,
        max_queue: int = 1024,
        max_batch_records: int = 512,
        prune_regions_above: int = 1024,
        tracer=None,
    ):
        if mode not in MODES:
            raise ValueError(f"durability mode must be one of {MODES}")
        if mode != "off" and wal is None:
            raise ValueError(f"durability={mode} requires a WriteAheadLog")
        self.store = store
        self.mode = mode
        self.wal = wal
        self.metrics = metrics
        self.tracer = tracer
        self._max_batch = max_batch_records
        self._rx = getattr(config, "db_region_x_size", 16)
        self._ry = getattr(config, "db_region_y_size", 256)
        self._rz = getattr(config, "db_region_z_size", 16)
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=max_queue)
        self._task: asyncio.Task | None = None
        self._handle = None  # SupervisedTask when run under a supervisor
        # sequence bookkeeping for barriers: _seq stamps every enqueued
        # op, _applied trails it as the applier finishes store calls
        self._seq = 0
        self._applied = 0
        self._region_seq: dict[tuple, int] = {}
        # amortized O(1) pruning: rebuild the map once it outgrows the
        # threshold, then set the next threshold to twice the survivors
        self._prune_min = prune_regions_above
        self._prune_at = prune_regions_above
        self._waiters: list[tuple[int, asyncio.Future]] = []
        self.apply_errors = 0
        #: insert/delete batches dropped on store errors — while > 0
        #: the server must NOT truncate the WAL (the dropped entries
        #: exist only there, awaiting boot-time replay). Dedupe drops
        #: don't count: they are derivable and never WAL-logged.
        self.dropped_batches = 0

    # region: lifecycle

    def start(self, supervisor=None) -> None:
        """Start the write-behind applier (wal mode only). Under a
        robustness.Supervisor the applier is a CRITICAL supervised
        task — a permanently dead applier means a filling queue that
        eventually backpressures every record handler, so budget
        exhaustion escalates to clean shutdown."""
        if self.mode != "wal":
            return
        if supervisor is not None:
            if self._handle is None:
                self._handle = supervisor.spawn(
                    "durability-applier", self._applier, critical=True
                )
        elif self._task is None:
            self._task = asyncio.create_task(
                self._applier(), name="durability-applier"
            )

    async def stop(self, drain_timeout: float = 30.0) -> bool:
        """Drain then stop the applier. Returns True when everything
        pending reached the store. On a wedged store the drain times
        out and pending ops are abandoned — every op acked to a client
        is in the WAL (the append resolves before the handler returns),
        so the next boot's recovery replays them (dedupe ops are the
        exception and are derivable)."""
        drained = True
        if self._task is not None or self._handle is not None:
            try:
                await asyncio.wait_for(self.drain(), drain_timeout)
            except asyncio.TimeoutError:
                drained = False
                logger.error(
                    "durability drain timed out with %d ops pending — "
                    "they remain in the WAL for boot-time replay",
                    self._seq - self._applied,
                )
        if self._handle is not None:
            await self._handle.stop()
            self._handle = None
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        return drained

    def stats(self) -> dict:
        out = {
            "mode": self.mode,
            "queue_depth": self._queue.qsize(),
            "enqueued": self._seq,
            "applied": self._applied,
            "apply_errors": self.apply_errors,
            "dropped_batches": self.dropped_batches,
        }
        if self.wal is not None:
            out.update(self.wal.stats())
        return out

    # endregion

    # region: record ops (the router's surface)

    def _span(self, name: str, **tags):
        """A handler-path span (one branch when tracing is off). These
        nest under the router's per-message handle span, so a slow
        record op shows its WAL/store split in the same trace."""
        tracer = self.tracer
        if tracer is None or not tracer.enabled:
            return NOOP_SPAN
        return tracer.span(name, **tags)

    async def insert_records(self, records: list[Record]) -> int:
        if self.mode == "off" or not records:
            failpoints.fire("store.insert")
            return await self.store.insert_records(records)
        if self.mode == "sync":
            with self._span("wal.append", kind="insert", n=len(records)):
                await self.wal.append(encode_insert(records))
            failpoints.fire("store.insert")
            return await self.store.insert_records(records)
        # enqueue BEFORE the WAL ack (module docstring: the ordering
        # invariant checkpoints rely on). If the append then fails the
        # op still reaches the store through the queue while the
        # handler raises — at-least-once, never an acked-but-lost write.
        await self._enqueue("insert", records)
        with self._span("wal.append", kind="insert", n=len(records)):
            await self.wal.append(encode_insert(records))
        return len(records)

    async def delete_records(self, records: list[Record]) -> int:
        if self.mode == "off" or not records:
            failpoints.fire("store.delete")
            return await self.store.delete_records(records)
        if self.mode == "sync":
            with self._span("wal.append", kind="delete", n=len(records)):
                await self.wal.append(encode_delete(records))
            failpoints.fire("store.delete")
            return await self.store.delete_records(records)
        await self._enqueue("delete", records)
        with self._span("wal.append", kind="delete", n=len(records)):
            await self.wal.append(encode_delete(records))
        return 0

    async def dedupe_records(self, ops: list[DedupeOp]) -> int:
        if self.mode != "wal" or not ops:
            return await self.store.dedupe_records(ops)
        await self._enqueue("dedupe", ops)
        return 0

    async def get_records_in_region(
        self, world_name: str, position: Vector3, after=None
    ) -> list[StoredRecord]:
        if self.mode == "wal":
            await self.read_barrier(world_name, position)
        return await self.store.get_records_in_region(
            world_name, position, after
        )

    # endregion

    # region: queue + barriers

    def _region_of(self, world: str, position) -> tuple:
        try:
            return (
                world,
                region_coords(
                    position.x, position.y, position.z,
                    self._rx, self._ry, self._rz,
                ),
            )
        except Exception:
            return _ALL_REGIONS

    def _regions_touched(self, kind: str, payload) -> set[tuple]:
        regions: set[tuple] = set()
        if kind == "dedupe":
            for _uuid, _ts, world, position in payload:
                regions.add(self._region_of(world, position))
        else:
            for record in payload:
                if record.position is None:
                    continue  # the store skips position-less records
                regions.add(self._region_of(record.world_name, record.position))
        return regions

    async def _enqueue(self, kind: str, payload) -> None:
        self._seq += 1
        seq = self._seq
        for region in self._regions_touched(kind, payload):
            self._region_seq[region] = seq
        if self._queue.full() and self.metrics is not None:
            self.metrics.inc("durability.backpressure_waits")
        await self._queue.put((seq, kind, payload))

    async def read_barrier(self, world: str, position) -> None:
        """Wait until every pending op touching (world, position)'s DB
        region has been applied to the store."""
        region = self._region_of(world, position)
        target = self._region_seq.get(_ALL_REGIONS, 0)
        if region == _ALL_REGIONS:
            # unquantizable read position: the store read will likely
            # fail anyway, but stay conservative and wait for everything
            target = self._seq
        else:
            target = max(target, self._region_seq.get(region, 0))
        await self._wait_applied(target)

    async def drain(self) -> None:
        """Wait until every op enqueued so far has been applied."""
        await self._wait_applied(self._seq)

    async def _wait_applied(self, target: int) -> None:
        if self._applied >= target:
            return
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append((target, fut))
        await fut

    def _wake_waiters(self) -> None:
        if not self._waiters:
            return
        still = []
        for target, fut in self._waiters:
            if self._applied >= target:
                if not fut.done():
                    fut.set_result(None)
            else:
                still.append((target, fut))
        self._waiters = still

    # endregion

    # region: applier

    async def _applier(self) -> None:
        """Drain the queue into batched store calls. Adjacent ops of the
        same kind coalesce into one ``executemany``-sized batch (order
        between kinds is preserved — an insert→delete pair for the same
        record can never invert). A store error drops that batch with a
        log line but still advances the applied watermark (barriers
        must never deadlock on a failing store); the drop is counted in
        ``dropped_batches``, which blocks WAL truncation so boot-time
        replay re-applies the entries (module docstring)."""
        pending: tuple | None = None
        while True:
            item = pending if pending is not None else await self._queue.get()
            pending = None
            seq, kind, payload = item
            batch = list(payload)
            while len(batch) < self._max_batch:
                try:
                    nxt = self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if nxt[1] != kind:
                    pending = nxt
                    break
                seq = nxt[0]
                batch.extend(nxt[2])
            with self._span("durability.apply", kind=kind, n=len(batch)):
                if self.metrics is not None:
                    with self.metrics.time_ms("durability.apply_ms"):
                        await self._apply(kind, batch)
                    self.metrics.inc("durability.applied_ops")
                else:
                    await self._apply(kind, batch)
            self._applied = seq
            # prune applied regions: at quiesce (empty queue) always,
            # under load once the map outgrows the doubling threshold —
            # amortized O(1) per batch either way
            if len(self._region_seq) > self._prune_min and (
                self._queue.qsize() == 0
                or len(self._region_seq) > self._prune_at
            ):
                applied = self._applied
                self._region_seq = {
                    r: s for r, s in self._region_seq.items() if s > applied
                }
                self._prune_at = max(
                    self._prune_min, 2 * len(self._region_seq)
                )
            self._wake_waiters()

    async def _apply(self, kind: str, batch: list) -> None:
        try:
            # write-behind boundary: an armed `durability.apply` drops
            # this batch exactly like a store error — counted, WAL
            # truncation blocked, replay re-applies it at next boot
            failpoints.fire("durability.apply")
            if kind == "insert":
                await self.store.insert_records(batch)
            elif kind == "delete":
                await self.store.delete_records(batch)
            else:
                await self.store.dedupe_records(batch)
        except asyncio.CancelledError:
            raise
        except Exception:
            self.apply_errors += 1
            if self.metrics is not None:
                self.metrics.inc("durability.apply_errors")
            if kind == "dedupe":
                logger.exception(
                    "write-behind dedupe batch of %d failed — dropped "
                    "(derivable: the next read of the region redoes it)",
                    len(batch),
                )
            else:
                self.dropped_batches += 1
                logger.exception(
                    "write-behind %s batch of %d failed — dropped from "
                    "the queue; WAL truncation is now disabled so "
                    "boot-time replay re-applies it",
                    kind, len(batch),
                )

    # endregion
