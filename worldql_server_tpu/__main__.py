"""CLI entry point.

Flag surface mirrors the reference's clap Args (worldql_server/src/
args.rs:21-129): every flag falls back to a ``WQL_*`` environment
variable (handled in Config), ``-v`` stacks verbosity
(main.rs:54-65), and validation failures exit 1 (main.rs:101-104).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys

from .engine.config import Config
from .engine.server import WorldQLServer
from . import __version__


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="worldql-server-tpu",
        description="TPU-native real-time spatial message broker",
    )
    p.add_argument("--version", action="version", version=__version__)
    p.add_argument("--store-url", help="record store url (sqlite://PATH, memory://, postgres://…)")
    p.add_argument("--sub-region-size", type=int, help="subscription cube size (default 16)")
    p.add_argument("--db-region-x-size", type=int)
    p.add_argument("--db-region-y-size", type=int)
    p.add_argument("--db-region-z-size", type=int)
    p.add_argument("--db-table-size", type=int)
    p.add_argument("--db-cache-size", type=int)
    p.add_argument("--http-host")
    p.add_argument("--http-port", type=int)
    p.add_argument("--http-auth-token")
    p.add_argument("--no-http", action="store_true")
    p.add_argument("--ws-host")
    p.add_argument("--ws-port", type=int)
    p.add_argument("--no-ws", action="store_true")
    p.add_argument("--zmq-server-host")
    p.add_argument("--zmq-server-port", type=int)
    p.add_argument("--zmq-timeout-secs", type=int)
    p.add_argument("--no-zmq", action="store_true")
    p.add_argument("--spatial-backend", choices=["cpu", "tpu", "sharded"])
    p.add_argument("--tick-interval", type=float)
    p.add_argument("--mesh-batch", type=int,
                   help="sharded backend: data-parallel query axis size")
    p.add_argument("--mesh-space", type=int,
                   help="sharded backend: space-shard axis size (0 = rest)")
    p.add_argument("-v", "--verbose", action="count", default=0)
    return p


_OVERRIDES = [
    "store_url", "sub_region_size", "db_region_x_size", "db_region_y_size",
    "db_region_z_size", "db_table_size", "db_cache_size", "http_host",
    "http_port", "http_auth_token", "ws_host", "ws_port", "zmq_server_host",
    "zmq_server_port", "zmq_timeout_secs", "spatial_backend", "tick_interval",
    "mesh_batch", "mesh_space",
]


def config_from_args(args: argparse.Namespace) -> Config:
    config = Config()
    for name in _OVERRIDES:
        value = getattr(args, name, None)
        if value is not None:
            setattr(config, name, value)
    config.http_enabled = not args.no_http
    config.ws_enabled = not args.no_ws
    config.zmq_enabled = not args.no_zmq
    config.verbose = args.verbose
    return config


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    level = [logging.WARNING, logging.INFO, logging.DEBUG][min(args.verbose, 2)]
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname)-7s %(name)s: %(message)s",
    )

    config = config_from_args(args)
    try:
        config.validate()
    except ValueError as exc:
        print(f"config error: {exc}", file=sys.stderr)
        return 1

    if config.spatial_backend == "sharded":
        # Mesh construction can reject shapes validate() can't see
        # (device count not divisible by mesh_batch); fail it as a
        # config error rather than a traceback from server bring-up.
        from .parallel.mesh import make_fanout_mesh

        try:
            make_fanout_mesh(config.mesh_batch, config.mesh_space or None)
        except ValueError as exc:
            print(f"config error: {exc}", file=sys.stderr)
            return 1

    server = WorldQLServer(config)
    try:
        asyncio.run(server.run_forever())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
