"""CLI entry point.

Flag surface mirrors the reference's clap Args (worldql_server/src/
args.rs:21-129): every flag falls back to a ``WQL_*`` environment
variable (handled in Config), a ``.env`` file loads before anything
reads the environment (main.rs:51), ``-v`` stacks verbosity
(main.rs:54-65), validation failures exit 1 (main.rs:101-104), and
each configured listening port is probed before bring-up so a busy
port dies with a named error instead of a bind traceback
(main.rs:73-98).
"""

from __future__ import annotations

import argparse
import asyncio
import errno
import logging
import os
import socket
import sys

from .engine.config import Config
from .engine.server import WorldQLServer
from .utils import trace
from .utils.dotenv import load_dotenv
from .utils.version import full_version
from . import __version__


class _VersionAction(argparse.Action):
    """Resolve the git hash only when --version is actually requested —
    the subprocess probe must not tax every server startup."""

    def __call__(self, parser, namespace, values, option_string=None):
        print(full_version(__version__))
        parser.exit(0)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="worldql-server-tpu",
        description="TPU-native real-time spatial message broker",
    )
    p.add_argument("--version", action=_VersionAction, nargs=0)
    p.add_argument("--store-url", help="record store url (sqlite://PATH, memory://, postgres://…)")
    p.add_argument("--sub-region-size", type=int, help="subscription cube size (default 16)")
    p.add_argument("--db-region-x-size", type=int)
    p.add_argument("--db-region-y-size", type=int)
    p.add_argument("--db-region-z-size", type=int)
    p.add_argument("--db-table-size", type=int)
    p.add_argument("--db-cache-size", type=int)
    p.add_argument("--http-host")
    p.add_argument("--http-port", type=int)
    p.add_argument("--http-auth-token")
    p.add_argument("--no-http", action="store_true")
    p.add_argument("--ws-host")
    p.add_argument("--ws-port", type=int)
    p.add_argument("--no-ws", action="store_true")
    p.add_argument("--zmq-server-host")
    p.add_argument("--zmq-server-port", type=int)
    p.add_argument("--zmq-timeout-secs", type=int)
    p.add_argument("--no-zmq", action="store_true")
    p.add_argument("--spatial-backend", choices=["cpu", "tpu", "sharded"])
    p.add_argument("--tick-interval", type=float)
    p.add_argument("--tick-pipeline", type=int,
                   help="max dispatched-but-undelivered ticks: 1 "
                        "(default) = sequential flush; 2 overlaps tick "
                        "N's collect+delivery with tick N+1's "
                        "accumulation and dispatch")
    p.add_argument("--query-staging", choices=["auto", "on", "off"],
                   dest="query_staging",
                   help="columnar query staging: enqueue-time encode "
                        "into double-buffered arrays so the tick flush "
                        "dispatches with zero per-query Python (auto = "
                        "on for staging-capable backends, the default; "
                        "off = object-list path everywhere)")
    p.add_argument("--query-kinds", choices=["on", "off"],
                   dest="query_kinds",
                   help="batched spatial query library: cone / raycast "
                        "/ filtered-kNN / region-density wire queries "
                        "(query.cone, query.raycast, query.knn, "
                        "query.density) expanded on the staged "
                        "columns; 'off' routes every parameter as a "
                        "plain radius match byte for byte (default on)")
    p.add_argument("--query-stencil-max", type=int,
                   dest="query_stencil_max",
                   help="cube-stencil radius cap for kind expansion, "
                        "applied at parse AND expansion (default 3)")
    p.add_argument("--query-ray-steps", type=int, dest="query_ray_steps",
                   help="max raycast march samples per query "
                        "(default 64)")
    p.add_argument("--query-density-top-n", type=int,
                   dest="query_density_top_n",
                   help="cubes kept per query.density reply and on "
                        "the wql_region_density gauge (default 16)")
    p.add_argument("--precompile-tiers", action="store_true",
                   default=None, dest="precompile_tiers_flag",
                   help="trace every reachable device-kernel capacity "
                        "tier at boot so no first-occurrence tier pays "
                        "a jit trace mid-serving (default on for "
                        "device backends)")
    p.add_argument("--no-precompile-tiers", action="store_true",
                   help="skip boot-time tier precompilation")
    p.add_argument("--mesh-batch", type=int,
                   help="sharded backend: data-parallel query axis size")
    p.add_argument("--mesh-space", type=int,
                   help="sharded backend: space-shard axis size (0 = rest)")
    p.add_argument("--index-snapshot",
                   help="subscription-index snapshot file: loaded at "
                        "boot if present, saved at shutdown")
    p.add_argument("--durability", choices=["off", "wal", "sync"],
                   help="record durability: off = inline store "
                        "(reference-equivalent), wal = group-committed "
                        "WAL + write-behind store, sync = WAL + inline "
                        "store (default off)")
    p.add_argument("--wal-dir",
                   help="WAL segment directory (default ./wal)")
    p.add_argument("--wal-fsync-ms", type=float,
                   help="group-commit batching window in ms; 0 (the "
                        "default) adds no wait — batches still form "
                        "naturally while an fsync is in flight")
    p.add_argument("--wal-segment-bytes", type=int,
                   help="WAL segment rotation threshold (default 64 MiB)")
    p.add_argument("--checkpoint-interval", type=float,
                   help="seconds between store-flush/snapshot/WAL-"
                        "truncate checkpoints; 0 = shutdown-only "
                        "(default 60)")
    p.add_argument("--max-message-size", type=int,
                   help="inbound wire-message byte cap, both transports "
                        "(default 8 MiB)")
    p.add_argument("--delivery-workers", type=int, dest="delivery_workers",
                   help="sender worker processes for the sharded "
                        "delivery plane: frames pump through per-worker "
                        "shared-memory rings to processes owning "
                        "disjoint socket shards; 0 (default) = the "
                        "single-process in-process pump")
    p.add_argument("--delivery-ring-bytes", type=int,
                   dest="delivery_ring_bytes",
                   help="per-worker fan-out ring capacity in bytes "
                        "(default 4 MiB; rounded up to a power of two)")
    p.add_argument("--failpoints",
                   help="arm fault-injection failpoints, e.g. "
                        "'store.insert=error:0.2,wal.fsync=delay:5ms' "
                        "(robustness/failpoints.py; default none)")
    p.add_argument("--failpoints-seed", type=int, dest="failpoints_seed",
                   help="deterministic RNG seed for probabilistic "
                        "failpoints (chaos runs)")
    p.add_argument("--failpoints-admin", action="store_true",
                   help="expose GET/POST /failpoints on the HTTP admin "
                        "surface (gated off by default)")
    p.add_argument("--resilience", choices=["off", "on"],
                   help="wrap the spatial backend in the degraded-mode "
                        "ResilientBackend: contain device failures, "
                        "rebuild from the CPU mirror, fail over "
                        "TPU->CPU after --failover-after consecutive "
                        "failures (default off)")
    p.add_argument("--failover-after", type=int, dest="failover_after",
                   help="consecutive backend failures before the "
                        "TPU->CPU failover (default 3)")
    p.add_argument("--supervisor-budget", type=int, dest="supervisor_budget",
                   help="restarts a supervised task gets per unhealthy "
                        "streak before it is marked failed (default 5)")
    p.add_argument("--supervisor-backoff", type=float,
                   dest="supervisor_backoff",
                   help="first restart backoff in seconds, doubling to "
                        "30s (default 0.5)")
    p.add_argument("--trace", action="store_true",
                   help="enable span tracing + the tick flight "
                        "recorder (observability/): per-stage tick "
                        "traces at GET /debug/ticks, loop-lag and "
                        "GC-pause histograms (default off)")
    p.add_argument("--slow-tick-ms", type=float, dest="slow_tick_ms",
                   help="auto-dump any tick slower than this many ms "
                        "(full span tree + loop health to "
                        "<slow-tick-dir>/slow-ticks.jsonl, CRITICAL "
                        "log); 0 dumps every tick; implies --trace "
                        "(default: no dumping)")
    p.add_argument("--slow-frame-ms", type=float, dest="slow_frame_ms",
                   help="cluster shards: auto-dump any cross-shard "
                        "frame whose router-ingress→socket-write wall "
                        "exceeds this many ms (stitched stage chain to "
                        "<slow-tick-dir>/slow-frames.jsonl, CRITICAL "
                        "log); 0 dumps every frame (default: no "
                        "dumping)")
    p.add_argument("--flight-recorder-depth", type=int,
                   dest="flight_recorder_depth",
                   help="tick traces kept in the flight-recorder ring "
                        "(default 64)")
    p.add_argument("--slow-tick-dir", dest="slow_tick_dir",
                   help="directory for slow-tick dump files "
                        "(default ./slow_ticks)")
    p.add_argument("--entity-sim", action="store_true",
                   help="entity simulation plane: clients register/"
                        "update entities over the wire (the entities "
                        "list on Local/GlobalMessage) and every ticker "
                        "flush integrates + resolves per-entity kNN on "
                        "device, delivering neighbor frames through "
                        "the fan-out path (requires a device backend "
                        "and --tick-interval > 0; default off)")
    p.add_argument("--entity-k", type=int, dest="entity_k",
                   help="neighbors resolved per entity per tick "
                        "(default 8)")
    p.add_argument("--entity-bounds", type=float, dest="entity_bounds",
                   help="world half-extent; positions reflect at "
                        "±bounds (default 1000)")
    p.add_argument("--entity-max", type=int, dest="entity_max",
                   help="live-entity hard cap (default 65536)")
    p.add_argument("--max-batch", type=int, dest="max_batch",
                   help="tick batch cap: a full queue flushes early; "
                        "also the overload governor's full-service "
                        "admitted tier (default 16384)")
    p.add_argument("--overload", choices=["off", "on"],
                   help="overload control plane: hysteretic OK/"
                        "SHED_LOW/SHED_HIGH/REJECT admission governor "
                        "— record ops never shed, globals shed last, "
                        "locals drop-oldest, entity updates coalesce "
                        "last-write-wins; per-peer token buckets; "
                        "tick-deadline degradation (default off = "
                        "today's behavior byte for byte)")
    p.add_argument("--overload-tick-budget-ms", type=float,
                   dest="overload_tick_budget_ms",
                   help="tick wall budget for deadline degradation in "
                        "ms (default 0 = derive from --tick-interval)")
    p.add_argument("--overload-deadline-k", type=int,
                   dest="overload_deadline_k",
                   help="consecutive budget busts before the admitted "
                        "batch tier halves (default 3)")
    p.add_argument("--overload-recover-ticks", type=int,
                   dest="overload_recover_ticks",
                   help="consecutive healthy samples per one-state "
                        "de-escalation / tier restore step (default 5)")
    p.add_argument("--overload-min-batch", type=int,
                   dest="overload_min_batch",
                   help="floor of the degraded admitted batch tier "
                        "(default 256)")
    p.add_argument("--overload-peer-rate", type=float,
                   dest="overload_peer_rate",
                   help="per-peer token bucket rate in msgs/s; record "
                        "ops are never dropped by it (default 0 = no "
                        "bucket)")
    p.add_argument("--overload-peer-burst", type=int,
                   dest="overload_peer_burst",
                   help="token bucket burst capacity (default 0 = "
                        "2x rate)")
    p.add_argument("--overload-evict-after", type=int,
                   dest="overload_evict_after",
                   help="evict a peer after this many consecutive "
                        "rate-limited messages (default 0 = never)")
    p.add_argument("--overload-rss-limit-mb", type=int,
                   dest="overload_rss_limit_mb",
                   help="RSS ceiling in MiB for the governor's memory "
                        "signal (default 0 = off)")
    p.add_argument("--session-ttl", type=float, dest="session_ttl",
                   help="park a dropped peer's subscriptions/entities "
                        "for this many seconds and let a reconnect "
                        "presenting its session token resume them with "
                        "zero index churn; 0 (default) = sessions off, "
                        "pre-session disconnect semantics byte for byte")
    p.add_argument("--delta-ticks", choices=["auto", "on", "off"],
                   dest="delta_ticks",
                   help="temporal-coherence delta ticks: per-cube "
                        "dirty bits, a persistent incrementally-"
                        "updated device hash, and result reuse for "
                        "clean queries/entities; 'auto' (default) "
                        "enables where supported (single-chip tpu), "
                        "'off' pins full recompute byte for byte")
    p.add_argument("--delta-rebuild-threshold", type=float,
                   dest="delta_rebuild_threshold",
                   help="churn fraction above which a delta structure "
                        "falls back to the full rebuild path "
                        "(default 0.5)")
    p.add_argument("--session-resume-rate", type=float,
                   dest="session_resume_rate",
                   help="resumes/s the overload governor still admits "
                        "in REJECT (new connects shed at SHED_HIGH+; "
                        "default 200)")
    p.add_argument("--cluster-shards", type=int, dest="cluster_shards",
                   help="horizontal serving: boot the router tier plus "
                        "this many supervised shard server processes "
                        "(world-sharded engines with per-shard WALs; "
                        "cross-shard delivery over inter-shard "
                        "shared-memory rings); 0 (default) = the "
                        "single-process server, byte for byte")
    p.add_argument("--cluster-role", choices=["router", "shard"],
                   dest="cluster_role",
                   help="cluster process role: 'router' (implied by "
                        "--cluster-shards N) or 'shard' (spawned by the "
                        "router-tier supervisor; requires the "
                        "WQL_CLUSTER_SPEC topology env)")
    p.add_argument("--autoshard", choices=["off", "on"],
                   dest="cluster_autoshard",
                   help="live resharding: 'on' arms the router-side "
                        "autoshard controller (watches federated "
                        "per-shard overload state, migrates the "
                        "hottest world off a sustained-hot shard); "
                        "'off' (default) keeps migrations manual via "
                        "POST /reshard")
    p.add_argument("--reshard-buffer-bytes", type=int,
                   dest="reshard_buffer_bytes",
                   help="byte budget for a migrating world's router-"
                        "side transfer buffer; overflow frames are "
                        "shed and counted, never silently lost "
                        "(default 8 MiB)")
    p.add_argument("--interest", choices=["off", "on"],
                   help="interest-managed fan-out: per-recipient "
                        "delta frames under a stamped epoch:seq wire "
                        "contract (entity.frame.full/fullc/delta) "
                        "with forced full-frame resync on every loss "
                        "path, LOD cadence tiers and per-peer "
                        "bandwidth budgets (requires --entity-sim; "
                        "default off = the broadcast delivery path "
                        "byte for byte)")
    p.add_argument("--lod-near-radius", type=float,
                   dest="lod_near_radius",
                   help="LOD cadence partition radius: neighbors "
                        "within this distance of the recipient's own "
                        "entity centroid deliver every tick, farther "
                        "ones every --lod-far-every-k ticks as "
                        "accumulated (lossless) diffs; 0 (default) "
                        "puts every neighbor in the near cohort")
    p.add_argument("--lod-far-every-k", type=int,
                   dest="lod_far_every_k",
                   help="far-cohort delivery cadence in ticks; the "
                        "overload governor's SHED tiers widen it "
                        "(k << level) instead of skipping frames "
                        "(default 4)")
    p.add_argument("--peer-bandwidth-bytes", type=int,
                   dest="peer_bandwidth_bytes",
                   help="per-peer delivery budget in bytes/s (token "
                        "bucket): an over-budget peer degrades "
                        "cadence first, then keyframe-only, and only "
                        "then sheds whole keyframes "
                        "(delivery.bytes_shed) — deltas are never "
                        "truncated (default 0 = off)")
    p.add_argument("--slo", choices=["off", "on"],
                   help="SLO engine: evaluate the objective registry "
                        "(frame/cluster e2e p99, drop/resync rates, "
                        "per-core delivery floor, WAL fsync p99) with "
                        "fast/slow-window burn-rate alerting — the slo "
                        "gauge, a /healthz block, and GET /debug/slo "
                        "(default off = no SLO surface at all)")
    p.add_argument("--slo-file", dest="slo_file",
                   help="JSON objective registry replacing the "
                        "built-in defaults (per-objective targets and "
                        "burn windows); implies --slo on")
    p.add_argument("--incident-dir", dest="incident_dir",
                   help="write one correlated incident capsule (JSON) "
                        "here on each SLO BURNING transition; bounded "
                        "ring of --incident-keep files, listed at "
                        "GET /debug/incidents (requires the SLO "
                        "engine)")
    p.add_argument("--incident-cooldown", type=float,
                   dest="incident_cooldown",
                   help="minimum seconds between incident capsules — "
                        "a flapping objective yields exactly one "
                        "capsule per window (default 60)")
    p.add_argument("--incident-keep", type=int, dest="incident_keep",
                   help="newest N incident capsules retained on disk "
                        "(default 16)")
    p.add_argument("--no-device-telemetry", action="store_true",
                   help="disable device telemetry (jit compile/retrace "
                        "counters + loose spans, per-tick encode/h2d/"
                        "compute/d2h split, live device-buffer gauge; "
                        "default on for device backends)")
    p.add_argument("-v", "--verbose", action="count", default=0)
    return p


_OVERRIDES = [
    "store_url", "sub_region_size", "db_region_x_size", "db_region_y_size",
    "db_region_z_size", "db_table_size", "db_cache_size", "http_host",
    "http_port", "http_auth_token", "ws_host", "ws_port", "zmq_server_host",
    "zmq_server_port", "zmq_timeout_secs", "spatial_backend", "tick_interval",
    "tick_pipeline", "query_staging", "query_kinds", "query_stencil_max",
    "query_ray_steps", "query_density_top_n", "mesh_batch", "mesh_space",
    "index_snapshot", "max_message_size",
    "durability", "wal_dir", "wal_fsync_ms", "wal_segment_bytes",
    "checkpoint_interval", "delivery_workers", "delivery_ring_bytes",
    "failpoints", "failpoints_seed", "resilience", "failover_after",
    "supervisor_budget", "supervisor_backoff",
    "slow_tick_ms", "slow_frame_ms", "flight_recorder_depth",
    "slow_tick_dir",
    "entity_k", "entity_bounds", "entity_max",
    "max_batch", "overload", "overload_tick_budget_ms",
    "overload_deadline_k", "overload_recover_ticks",
    "overload_min_batch", "overload_peer_rate", "overload_peer_burst",
    "overload_evict_after", "overload_rss_limit_mb",
    "session_ttl", "session_resume_rate",
    "delta_ticks", "delta_rebuild_threshold",
    "cluster_shards", "cluster_role", "cluster_autoshard",
    "reshard_buffer_bytes",
    "interest", "lod_near_radius", "lod_far_every_k",
    "peer_bandwidth_bytes",
    "slo", "slo_file", "incident_dir", "incident_cooldown", "incident_keep",
]


def config_from_args(args: argparse.Namespace) -> Config:
    config = Config()
    for name in _OVERRIDES:
        value = getattr(args, name, None)
        if value is not None:
            setattr(config, name, value)
    config.http_enabled = not args.no_http
    config.ws_enabled = not args.no_ws
    config.zmq_enabled = not args.no_zmq
    if args.failpoints_admin:
        config.failpoints_admin = True
    if args.trace:
        config.trace = True
    if args.no_device_telemetry:
        config.device_telemetry = False
    if args.entity_sim:
        config.entity_sim = True
    if args.precompile_tiers_flag:
        config.precompile_tiers = True
    if args.no_precompile_tiers:
        config.precompile_tiers = False
    config.verbose = args.verbose
    return config


def _port_is_free(host: str, port: int) -> bool:
    """True unless the port is definitely taken. Resolves the address
    family (IPv6 hosts probe as IPv6), and treats only EADDRINUSE as
    busy — any other failure (unresolvable host, privileged port) is
    deferred to the real bind, which reports it accurately."""
    try:
        infos = socket.getaddrinfo(
            host or None, port, type=socket.SOCK_STREAM,
            flags=socket.AI_PASSIVE,
        )
    except socket.gaierror:
        return True
    family, type_, proto, _, addr = infos[0]
    try:
        with socket.socket(family, type_, proto) as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(addr)
    except OSError as exc:
        return exc.errno != errno.EADDRINUSE
    return True


def check_ports(config: Config) -> str | None:
    """Probe each enabled listening port; returns an error naming the
    offending flag, or None (main.rs:73-98 portpicker parity)."""
    probes = []
    if config.ws_enabled:
        probes.append(("WebSocket server", "--ws-port",
                       config.ws_host, config.ws_port))
    if config.http_enabled:
        probes.append(("HTTP server", "--http-port",
                       config.http_host, config.http_port))
    if config.zmq_enabled:
        probes.append(("ZeroMQ server", "--zmq-server-port",
                       config.zmq_server_host, config.zmq_server_port))
    for what, flag, host, port in probes:
        if not _port_is_free(host, port):
            return f"{what} port {port} ({flag}) is already in use"
    return None


def main(argv: list[str] | None = None) -> int:
    load_dotenv()
    args = build_parser().parse_args(argv)

    # -v stacks: warning → info → debug → trace-with-packet-dumps
    # (main.rs:54-65: verbosity 3 turns on the per-packet channel)
    levels = [logging.WARNING, logging.INFO, logging.DEBUG, trace.TRACE_LEVEL]
    logging.basicConfig(
        level=levels[min(args.verbose, 3)],
        format="%(asctime)s %(levelname)-7s %(name)s: %(message)s",
    )
    # re-check after load_dotenv(): the env var may have come from the
    # .env file, which loads after trace.py's import-time read
    if args.verbose >= 3 or os.environ.get("WQL_TRACE_PACKETS") == "1":
        trace.enable()

    config = config_from_args(args)
    # Default-on device boot (ROADMAP 5): with an accelerator attached
    # and no backend preference expressed, a bare invocation serves the
    # batched device engine; a CPU-only host keeps the config untouched.
    from .engine.config import apply_device_boot_defaults

    apply_device_boot_defaults(
        config,
        backend_explicit=args.spatial_backend is not None,
        interval_explicit=args.tick_interval is not None,
    )
    try:
        config.validate()
    except ValueError as exc:
        print(f"config error: {exc}", file=sys.stderr)
        return 1

    port_error = check_ports(config)
    if port_error is not None:
        print(f"config error: {port_error}", file=sys.stderr)
        return 1

    if config.spatial_backend == "sharded":
        # Mesh construction can reject shapes validate() can't see
        # (device count not divisible by mesh_batch); fail it as a
        # config error rather than a traceback from server bring-up.
        from .parallel.mesh import make_fanout_mesh

        try:
            make_fanout_mesh(config.mesh_batch, config.mesh_space or None)
        except ValueError as exc:
            print(f"config error: {exc}", file=sys.stderr)
            return 1

    if config.cluster_shards > 0:
        # Router tier: the public listener + the supervised shard
        # processes. Never constructs a WorldQLServer of its own —
        # every world lives in exactly one shard.
        from .cluster import ClusterRuntime

        runtime = ClusterRuntime(config)
        try:
            asyncio.run(runtime.run_forever())
        except KeyboardInterrupt:
            pass
        return 0

    server = WorldQLServer(config)
    try:
        asyncio.run(server.run_forever())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
