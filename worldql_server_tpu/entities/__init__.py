"""Entity simulation plane (ISSUE 9).

The wire protocol has carried ``Message.entities`` since the reference
(structures/entity.rs) — this package is the first thing that USES it:
``--entity-sim`` turns the broker into a spatial simulation loop.
Clients register/update entities over the existing Local/GlobalMessage
envelope, :class:`EntityPlane` owns the device-resident ``EntityState``
SoA, and every ticker flush integrates positions, re-quantizes, and
resolves per-entity kNN neighborhoods on device (ops/tick.py) — the
resulting neighbor frames fan out through the same delivery plane as
every other broadcast.
"""

from .ingest import ColumnarIngest
from .plane import PARAM_FRAME, PARAM_REMOVE, EntityPlane, WireFrame

__all__ = [
    "ColumnarIngest",
    "EntityPlane",
    "PARAM_FRAME",
    "PARAM_REMOVE",
    "WireFrame",
]
