"""EntityPlane: the device-resident moving-object workload.

One plane owns every live entity as a slot in preallocated host SoA
columns (``pos f32[cap,3] | vel f32[cap,3] | wid i32 | pid i32``) plus
their device twin, an :class:`~worldql_server_tpu.ops.tick.EntityState`.
The host columns are the authority (the same discipline as
spatial/tpu_backend.py): wire ingest mutates them at message-arrival
time, each ticker flush runs ONE jitted ``simulation_tick`` (integrate
→ re-quantize → spatial-hash rebuild → stencil kNN, ops/tick.py), and
the collect fetches back integrated positions + per-entity neighbor
targets.

Columnar ingest (PR 11): updates of LIVE entities stage into fixed
preallocated double-buffered columns (``pos/vel/has_vel/touched`` per
slot) instead of writing per-entity — coalescing IS the column
overwrite (last write per slot wins, per field), and the pre-dispatch
drain is a buffer flip + one vectorized masked fold into the authority
columns. The wire fast path (``ingest_columns``, fed by
protocol/entity_wire.wql_decode_entities through entities/ingest.py)
maps a whole recv batch's uuid keys to slots in one C-level pass and
stages every owned row without constructing a single Entity object;
registrations, removals, and exotic messages keep the object path
(``ingest``) — identical semantics, per-entity cost, control-plane
rates. The device twin is maintained INCREMENTALLY: a dirty-slot
bitmap tracks rows whose host authority diverged from the twin
(client updates, registrations, removals), and each dispatch scatters
only those rows into device memory (ASH-style partial transfer,
arXiv:2110.00511) instead of re-shipping whole columns — the scatter
kernel registers with the retrace GUARD under ``entities.scatter`` and
its pow2 dirty-bucket ladder precompiles at boot.

Capacity is a power-of-two tier (``_MIN_CAP`` floor), so the jitted
tick sees a handful of shapes over a process lifetime — the tick
kernel registers with the retrace GUARD under ``entities.sim_tick``
and the e2e suite holds the steady-state budget.

Index coupling (the bounded-staleness contract): every entity also
owns ONE subscription row in the authoritative spatial index — its
owner peer subscribed at the entity's current cube — refcounted per
``(world, cube, peer)`` so co-located entities of one peer share a
row. Registration inserts the row IMMEDIATELY (a new entity is
queryable before its first tick); position churn flows through the
index's base+delta path (``bulk_move_subscriptions``) when the tick's
integrated position crosses a cube boundary. Subscription queries
therefore observe an entity's position with staleness bounded by ONE
applied tick: the cube registered in the index is the quantization of
the position the LAST applied tick integrated (plus any not-yet-ticked
wire update, which re-quantizes at the next apply). Entity state and
index can never diverge structurally — both are derived from the same
host columns, and the index mutation happens in the same event-loop
turn as the position writeback.

Tick-path discipline: ``dispatch_tick``/``collect_tick`` are the
sim-tick hot functions — no per-entity Python, host syncs only at the
designated collect points (tools/check: host-sync-in-sim-tick). Frame
assembly and index churn (``apply``) are host delivery/index work,
O(fan-out) and O(churn) respectively, and run on the event loop like
the router's per-message handling.
"""

from __future__ import annotations

import itertools
import logging
import time
import uuid as uuid_mod
from collections import Counter

import numpy as np

from ..spatial import jaxconf  # noqa: F401  (must precede jax import)
import jax
import jax.numpy as jnp

from ..ops.tick import EntityState, make_tick_fn
from ..protocol import entity_wire
from ..robustness import failpoints
from ..protocol.types import Entity, Instruction, Message, Vector3
from ..spatial.hashing import spatial_keys
from ..spatial.quantize import cube_coords_batch
from ..utils.names import SanitizeError, sanitize_world_name
from ..utils.retrace import GUARD

logger = logging.getLogger(__name__)

#: Message.parameter marking an entity-removal batch (any other
#: parameter — usually None — upserts the carried entities)
PARAM_REMOVE = "entity.remove"
#: Message.parameter stamped on outbound neighbor frames
PARAM_FRAME = "entity.frame"

#: smallest capacity tier (pow2); arrays never shrink below it
_MIN_CAP = 256
#: parked coordinate for dead slots: quantizes to the saturated cube of
#: the dead world (wid -1), far outside any live neighborhood
_DEAD_POS = np.float32(1.0e30)
#: smallest dirty-row scatter bucket (pow2 ladder floor): below this the
#: fixed launch cost dominates and finer tiers only multiply compiles
_SCATTER_MIN_BUCKET = 64
#: smallest delta-tick sub-batch tier (pow2 ladder floor): the dirty
#: closure pads up to this before the sub-kernel launches, so steady
#: low-churn serving reuses a handful of compiled shapes
_DELTA_MIN_TIER = 64
#: world-name fallback envelope for wire-path registrations (the world
#: is always resolved before this is consulted)
_WIRE_MSG = Message(instruction=Instruction.LOCAL_MESSAGE)


def _next_pow2(n: int) -> int:
    return 1 << (max(n, 1) - 1).bit_length()


class WireFrame:
    """A pre-encoded outbound frame: ready wire bytes standing in for a
    Message in ``PeerMap.deliver_batch`` pairs (which reads ``.wire``
    and never re-serializes when it is set). The native per-cohort
    frame encode hands these out so the apply leg constructs no
    per-entity Message objects. Message attributes (``entities``,
    ``parameter``, …) resolve lazily by decoding the wire bytes —
    diagnostics-only; the delivery path never triggers it."""

    __slots__ = ("wire", "_msg")

    def __init__(self, wire: bytes):
        self.wire = wire
        self._msg = None

    def __getattr__(self, name):
        msg = object.__getattribute__(self, "_msg")
        if msg is None:
            from ..protocol import deserialize_message

            msg = deserialize_message(self.wire)
            object.__setattr__(self, "_msg", msg)
        return getattr(msg, name)


class _StageBuf:
    """One side of the double-buffered update-staging columns: the LWW
    coalescing surface. ``touched[slot]`` marks a staged position;
    ``has_vel[slot]`` marks a staged velocity (fields coalesce
    independently, exactly like sequential application)."""

    __slots__ = ("pos", "vel", "has_vel", "touched", "dirty")

    def __init__(self, cap: int):
        self.pos = np.zeros((cap, 3), np.float32)
        self.vel = np.zeros((cap, 3), np.float32)
        self.has_vel = np.zeros(cap, bool)
        self.touched = np.zeros(cap, bool)
        self.dirty = False  # any touched bit set since the last flip

    def grow(self, cap: int) -> None:
        old = self.touched.shape[0]
        for name in ("pos", "vel"):
            out = np.zeros((cap, 3), np.float32)
            out[:old] = getattr(self, name)
            setattr(self, name, out)
        for name in ("has_vel", "touched"):
            out = np.zeros(cap, bool)
            out[:old] = getattr(self, name)
            setattr(self, name, out)


def _scatter_update(state: EntityState, idx, pos, vel, wid, pid):
    """Scatter dirty host rows into the device twin — the incremental
    H2D leg (only touched slots ship, never whole columns). ``idx`` is
    padded to its pow2 bucket with the out-of-range capacity value;
    ``mode='drop'`` discards those lanes on device."""
    return EntityState(
        position=state.position.at[idx].set(pos, mode="drop"),
        velocity=state.velocity.at[idx].set(vel, mode="drop"),
        world=state.world.at[idx].set(wid, mode="drop"),
        peer=state.peer.at[idx].set(pid, mode="drop"),
    )


class EntityPlane:
    """Device-resident entity population + its authoritative-index
    coupling for one server. Event-loop owned except where noted."""

    def __init__(
        self,
        backend,
        peer_map,
        *,
        cube_size: int,
        k: int = 8,
        dt: float = 0.05,
        bounds: float = 1000.0,
        max_entities: int = 1 << 16,
        metrics=None,
        tracer=None,
        governor=None,
        wire="auto",
        delta_ticks: str = "off",
        delta_rebuild_threshold: float = 0.5,
    ):
        self.backend = backend
        self.peer_map = peer_map
        self.cube_size = cube_size
        self.k = int(k)
        self.dt = float(dt)
        self.bounds = float(bounds)
        self.max_entities = int(max_entities)
        self.metrics = metrics
        self.tracer = tracer
        # Optional robustness.overload.OverloadGovernor: under
        # SHED_LOW+ updates of LIVE entities coalesce last-write-wins
        # per slot into the staging columns and apply once per tick —
        # lossless for position streams (the newest value per field
        # subsumes the ones it overwrote). Registrations and removals
        # always apply immediately (control plane).
        self._governor = governor
        self.coalesced = 0
        self.frames_skipped = 0

        # host SoA columns (authority; slot-indexed, pow2 capacity)
        self._cap = _MIN_CAP
        self._pos = np.full((self._cap, 3), _DEAD_POS, np.float32)
        self._vel = np.zeros((self._cap, 3), np.float32)
        self._wid = np.full(self._cap, -1, np.int32)
        self._pid = np.full(self._cap, -1, np.int32)
        #: cube currently registered in the authoritative index
        self._cube = np.zeros((self._cap, 3), np.int64)
        self._live = np.zeros(self._cap, bool)
        #: slots mutated by wire ingest since the LAST dispatch — the
        #: post-tick position writeback must not clobber them
        self._touched = np.zeros(self._cap, bool)
        #: binary uuid per slot (frame encode + wire-path slot map)
        self._uuid_bytes = np.zeros((self._cap, 16), np.uint8)
        #: double-buffered update-staging columns: ingest writes the
        #: active side; the pre-dispatch drain flips and folds the
        #: retired side in one vectorized pass (replaces the per-uuid
        #: _pending dict of PR 10)
        self._stage = [_StageBuf(self._cap), _StageBuf(self._cap)]
        self._stage_active = 0
        #: slots whose host authority diverged from the device twin
        #: since its last upload — the incremental-H2D scatter set
        self._device_dirty = np.zeros(self._cap, bool)
        self._dev_state: EntityState | None = None
        self._dev_cap = 0

        # Delta sim ticks (ROADMAP 2): instead of re-running the full
        # integrate→sort→kNN kernel over every slot each tick, gather
        # the DIRTY-CUBE CLOSURE — all live entities in any cube a
        # dirty entity occupies now or can reach this tick — into a
        # pow2 sub-batch, run the SAME tick kernel at that (smaller)
        # tier, and splice the results over the retained last-tick
        # arrays; clean entities replay. Requires a pow2 cube size:
        # the host-side reach prediction replays the device's f32
        # integration bit-for-bit and quantizes with the golden host
        # quantizer, whose agreement with the device quantizer is
        # pinned EXACT for pow2 sizes (tests/test_quantizer_envelope).
        pow2_cube = cube_size == _next_pow2(cube_size)
        self._delta_ticks = delta_ticks in ("on", "auto") and pow2_cube
        if delta_ticks == "on" and not pow2_cube:
            logger.warning(
                "delta_ticks='on' needs a power-of-two cube size for "
                "the exact quantizer envelope (got %d) — running full "
                "recompute ticks", cube_size,
            )
        self.delta_rebuild_threshold = float(delta_rebuild_threshold)
        #: slots mutated since the last SUCCESSFUL dispatch (the delta
        #: dirty stream; _device_dirty can't serve — it clears on H2D)
        self._window_dirty = np.zeros(self._cap, bool)
        #: (wid, cx, cy, cz) cubes vacated by removals this window —
        #: the slot's wid/cube columns are wiped at release time
        self._window_dirty_cubes: list[tuple] = []
        #: retained last applied tick (the replay source)
        self._have_last = False
        self._last_cap = 0
        self._last_targets: np.ndarray | None = None
        self._last_counts: np.ndarray | None = None
        self._last_pos: np.ndarray | None = None
        self.delta_sim_ticks = 0
        self.full_sim_ticks = 0
        self.delta_reused = 0
        self.delta_recomputed = 0
        self.delta_fallbacks = 0
        self.delta_mispredicts = 0
        self.last_delta_stats: dict = {}

        self._n = 0                     # slot high-water mark
        self._free: list[int] = []      # recycled slots below _n
        self._slot_of: dict[uuid_mod.UUID, int] = {}
        #: 16-byte uuid key → slot (the wire path's C-level bulk map)
        self._slot_of_key: dict[bytes, int] = {}
        self._uuid_of: dict[int, uuid_mod.UUID] = {}

        # interning (plane-local dense ids; the INDEX interns its own)
        self._world_ids: dict[str, int] = {}
        self._world_names: list[str] = []
        self._peer_ids: dict[uuid_mod.UUID, int] = {}
        self._peer_uuids: list[uuid_mod.UUID] = []
        #: binary uuid per dense peer id (cohort frame senders)
        self._peer_key_arr = np.zeros((64, 16), np.uint8)
        #: per-peer entity slots (eviction sweep)
        self._peer_slots: dict[int, set[int]] = {}

        # native columnar wire codec: "auto" = the shared in-tree
        # library (symbol-probed; stale .so → None and every leg
        # degrades to the object path), None/instance for tests
        self._wire = entity_wire.shared() if wire == "auto" else wire

        #: interest manager (``--interest on``): when set, apply()
        #: routes the frame leg through per-recipient delta frames
        #: instead of _build_frames. None (the default) keeps the
        #: legacy broadcast path byte for byte — the manager is never
        #: consulted, constructed, or imported on that path.
        self.interest = None

        #: (wid, cx, cy, cz, pid) → live-entity refcount backing ONE
        #: index row; transitions through 0 mutate the index
        self._sub_refs: Counter = Counter()

        # one jitted tick fn; shape (= capacity tier) keys its compile
        # cache, which the retrace GUARD audits under entities.sim_tick
        self._tick_fn = jax.jit(
            make_tick_fn(
                cube_size=cube_size, k=self.k, dt=self.dt,
                bounds=self.bounds,
            )
        )
        GUARD.register("entities.sim_tick", self._tick_fn)
        # incremental H2D: one jitted scatter, shape-keyed on
        # (capacity tier, dirty bucket) — the ladder precompiles at boot
        self._scatter_fn = jax.jit(_scatter_update)
        GUARD.register("entities.scatter", self._scatter_fn)
        self._tick_inflight = False

        # stats (exposed via the entity_sim gauge + bench config 8)
        self.entities_registered = 0
        self.entities_removed = 0
        self.updates = 0
        self.rejected = 0
        self.dispatches = 0
        self.applied_ticks = 0
        self.dropped_ticks = 0
        self.frames = 0
        self.index_moves = 0
        self.last_integrate_ms = 0.0
        self.last_knn_ms = 0.0
        self.last_apply_ms = 0.0
        self.last_churn = 0
        # columnar-path stats (wire rows staged with zero per-entity
        # Python; flips; H2D split; native cohort-encoded frames)
        self.wire_rows = 0
        self.wire_slow_rows = 0
        self.column_flips = 0
        self.h2d_full = 0
        self.h2d_scatter = 0
        self.scatter_fallbacks = 0  # scatter errors → full upload
        self.last_h2d_rows = 0
        self.frames_native = 0
        # Frame-level reuse (ISSUE 14 satellite, the PR 13 leftover):
        # a cohort whose membership AND member positions did not
        # change since last tick replays last tick's encoded wire
        # bytes instead of re-running wql_encode_entity_frames —
        # keyed by the cohort key, guarded by exact row/position
        # byte equality, invalidated wholesale by any slot identity
        # change (registration/removal clears it: uuid/pid bytes at a
        # reused slot would otherwise alias a stale frame).
        self._frame_cache: dict[bytes, tuple] = {}
        self.frames_reused = 0

    # region: wire ingest (router arrival path)

    @property
    def entity_count(self) -> int:
        return len(self._slot_of)

    def active(self) -> bool:
        return bool(self._slot_of)

    def ingest(self, message: Message) -> int:
        """Apply one inbound entity batch THE OBJECT WAY: upsert every
        carried Entity (or remove, when ``parameter ==
        'entity.remove'``) for the sending peer. This is the semantic
        reference and the fallback for everything the columnar wire
        path (``ingest_columns``) routes around — removals, exotic
        parameters/uuid formats, per-entity worlds, a stale native
        library. Returns entities applied."""
        sender = message.sender_uuid
        removing = message.parameter == PARAM_REMOVE
        governor = self._governor
        coalesce = (
            not removing
            and governor is not None
            and governor.coalesce_entities()
        )
        applied = 0
        for ent in message.entities:  # wql: allow(per-entity-python-ingest) — the object-path semantic reference; hot traffic rides ingest_columns
            try:
                if removing:
                    applied += self._remove_entity(ent.uuid, sender)
                elif coalesce and ent.uuid in self._slot_of:
                    applied += self._stage_update(ent, message, sender)
                else:
                    applied += self._upsert(ent, message, sender)
            except SanitizeError as exc:
                logger.warning(
                    "peer %s sent entity with invalid world %r (%s)",
                    sender, ent.world_name or message.world_name, exc,
                )
        if applied and self.metrics is not None:
            self.metrics.inc("sim.updates", applied)
        self.updates += applied
        return applied

    def _stage_update(self, ent: Entity, message: Message,
                      sender: uuid_mod.UUID) -> int:
        """Coalescing admission (governor SHED_LOW+), object-path leg:
        stage the update of a LIVE entity into the columnar staging
        buffer — coalescing IS the column overwrite (last write per
        slot wins, per field); ``_drain_pending`` folds the survivors
        in one vectorized pass at the next dispatch. Ownership and
        world sanitation are enforced HERE so a hostile update can't
        hide in the staging columns. An overwrite counts as
        ``overload.coalesced`` — shed-but-lossless work (the audit
        invariant: offered == applied + coalesced + dropped)."""
        sanitize_world_name(ent.world_name or message.world_name)
        slot = self._slot_of[ent.uuid]
        owner = self._peer_uuids[self._pid[slot]]
        if owner != sender:
            logger.warning(
                "peer %s sent update for entity %s owned by %s — "
                "dropped", sender, ent.uuid, owner,
            )
            return 0
        buf = self._stage[self._stage_active]
        first = not buf.touched[slot]
        p = ent.position
        buf.pos[slot, 0] = p.x
        buf.pos[slot, 1] = p.y
        buf.pos[slot, 2] = p.z
        vel = _decode_velocity(ent.flex)
        if vel is not None:
            buf.vel[slot] = vel
            buf.has_vel[slot] = True
        buf.touched[slot] = True
        buf.dirty = True
        if first:
            return 1
        self.coalesced += 1
        if self.metrics is not None:
            self.metrics.inc("overload.coalesced")
        return 0

    def _drain_pending(self) -> None:
        """Fold the staged update columns into the host authority —
        the buffer flip that replaced PR 10's per-uuid dict walk: flip
        the double buffer (ingest keeps writing the fresh side), then
        apply the retired side's touched rows as one masked copy per
        column. The coalescing staleness bound is the same one tick
        the plane already documents."""
        buf = self._stage[self._stage_active]
        if not buf.dirty:
            return
        self._stage_active ^= 1
        rows = np.flatnonzero(buf.touched)
        self._pos[rows] = buf.pos[rows]
        hv = rows[buf.has_vel[rows]]
        if hv.size:
            self._vel[hv] = buf.vel[hv]
        # a client update must win over the in-flight tick's writeback,
        # and its rows must ship to the device twin at this dispatch
        self._touched[rows] = True
        self._device_dirty[rows] = True
        self._window_dirty[rows] = True
        buf.touched[rows] = False
        buf.has_vel[rows] = False
        buf.dirty = False
        self.column_flips += 1

    def staged_count(self) -> int:
        """Touched rows awaiting the next flip (test/gauge probe)."""
        return int(np.count_nonzero(self._stage[self._stage_active].touched))

    def is_staged(self, eid: uuid_mod.UUID) -> bool:
        slot = self._slot_of.get(eid)
        if slot is None:
            return False
        return bool(self._stage[self._stage_active].touched[slot])

    def ingest_columns(
        self,
        senders: list,
        worlds: list,
        counts: np.ndarray,
        uuid_keys: np.ndarray,
        pos: np.ndarray,
        vel: np.ndarray,
        has_vel: np.ndarray,
    ) -> int:
        """Wire→SoA fast path: stage a whole recv batch's entity
        updates with zero per-entity Python. ``senders``/``worlds`` are
        per message; ``counts[i]`` rows of the shared columns belong to
        message i. uuid→slot mapping is one C-level bulk dict pass;
        ownership is enforced vectorized at stage time; position/
        velocity staging is a fancy-indexed column overwrite whose
        last-write-wins order is exactly arrival order. Only rows whose
        uuid is unknown (registrations — control-plane rates) take the
        per-entity object path. Returns entities applied, mirroring
        ``ingest``'s accounting."""
        n_bufs = len(senders)
        total = int(counts.sum())
        if total == 0:
            return 0
        pids = np.empty(n_bufs, np.int32)
        buf_ok = np.ones(n_bufs, bool)
        for b in range(n_bufs):
            try:
                worlds[b] = sanitize_world_name(worlds[b])
                pids[b] = self._peer_ids.get(senders[b], -1)
            except SanitizeError as exc:
                logger.warning(
                    "peer %s sent entity batch with invalid world %r "
                    "(%s)", senders[b], worlds[b], exc,
                )
                buf_ok[b] = False
                pids[b] = -1
        row_buf = np.repeat(np.arange(n_bufs), counts)
        row_ok = buf_ok[row_buf]
        exp_pid = pids[row_buf]

        # V16 (not S16): bytes_ views strip trailing NULs, void keeps
        # all 16 bytes — the keys must match uuid.bytes exactly
        keys = uuid_keys.reshape(total, 16).view("V16").ravel().tolist()
        slots = np.fromiter(
            map(self._slot_of_key.get, keys, itertools.repeat(-1)),
            np.int64, count=total,
        )
        hit = (slots >= 0) & row_ok
        safe = np.where(hit, slots, 0)
        owned = hit & (self._pid[safe] == exp_pid)
        stolen = int(hit.sum()) - int(owned.sum())
        if stolen:
            logger.warning(
                "%d entity updates for entities their senders do not "
                "own — dropped", stolen,
            )

        applied = 0
        orows = np.flatnonzero(owned)
        if orows.size:
            s = slots[orows]
            buf = self._stage[self._stage_active]
            governor = self._governor
            if governor is not None and governor.coalesce_entities():
                # dict-parity accounting: first stage per slot applies,
                # every overwrite (intra-batch duplicates included)
                # counts as coalesced — shed-but-lossless
                uniq = np.unique(s)
                fresh = int(np.count_nonzero(~buf.touched[uniq]))
                over = int(orows.size) - fresh
                if over:
                    self.coalesced += over
                    if self.metrics is not None:
                        self.metrics.inc("overload.coalesced", over)
                applied += fresh
            else:
                applied += int(orows.size)
            buf.pos[s] = pos[orows]
            hv = has_vel[orows].astype(bool)
            if hv.any():
                sv = s[hv]
                buf.vel[sv] = vel[orows][hv]
                buf.has_vel[sv] = True
            buf.touched[s] = True
            buf.dirty = True
            self.wire_rows += int(orows.size)

        # unknown uuids: registrations (or intra-batch updates of one
        # just registered) — the per-entity object path is the right
        # cost for this control-plane traffic, and re-probing the slot
        # map per row keeps intra-batch arrival order exact
        miss = row_ok & (slots < 0)
        for r in np.flatnonzero(miss).tolist():  # wql: allow(per-entity-python-ingest) — registrations only; update traffic stays columnar
            b = int(row_buf[r])
            applied += self._wire_slow_row(
                keys[r], worlds[b], pos[r], vel[r], bool(has_vel[r]),
                senders[b],
            )
            self.wire_slow_rows += 1

        if applied:
            self.updates += applied
            if self.metrics is not None:
                self.metrics.inc("sim.updates", applied)
        return applied

    def _wire_slow_row(self, key: bytes, world: str, p, v,
                       has_v: bool, sender: uuid_mod.UUID) -> int:
        """One columnar row routed through the object path (its uuid
        was unknown at batch start): registration — or, for a uuid
        registered earlier in the same batch, a normal owned update."""
        ent = Entity(
            uuid=uuid_mod.UUID(bytes=key),
            position=Vector3(float(p[0]), float(p[1]), float(p[2])),
            world_name=world,
            flex=v.tobytes() if has_v else None,
        )
        try:
            return self._upsert(ent, _WIRE_MSG, sender)
        except SanitizeError:
            return 0  # world sanitized upstream; belt and braces

    def _upsert(self, ent: Entity, message: Message,
                sender: uuid_mod.UUID) -> int:
        world = sanitize_world_name(ent.world_name or message.world_name)
        slot = self._slot_of.get(ent.uuid)
        new = slot is None
        if new:
            if len(self._slot_of) >= self.max_entities:
                self.rejected += 1
                if self.metrics is not None:
                    self.metrics.inc("sim.rejected")
                logger.warning(
                    "entity registration rejected: plane full "
                    "(%d >= max_entities %d)",
                    len(self._slot_of), self.max_entities,
                )
                return 0
            slot = self._alloc_slot(ent.uuid, sender, world)
            self.entities_registered += 1
        else:
            owner = self._peer_uuids[self._pid[slot]]
            if owner != sender:
                # an entity belongs to the peer that registered it;
                # a hijacking update is dropped, not transferred
                logger.warning(
                    "peer %s sent update for entity %s owned by %s — "
                    "dropped", sender, ent.uuid, owner,
                )
                return 0
        p = ent.position
        self._pos[slot, 0] = p.x
        self._pos[slot, 1] = p.y
        self._pos[slot, 2] = p.z
        vel = _decode_velocity(ent.flex)
        if vel is not None:
            self._vel[slot] = vel
        self._touched[slot] = True
        self._device_dirty[slot] = True
        self._window_dirty[slot] = True
        if new:
            # index coupling: queryable before the first tick
            self._register_cube(slot)
        return 1

    def _alloc_slot(self, uuid: uuid_mod.UUID, sender: uuid_mod.UUID,
                    world: str) -> int:
        if self._free:
            slot = self._free.pop()
        else:
            if self._n == self._cap:
                self._grow(self._cap * 2)
            slot = self._n
            self._n += 1
        wid = self._world_ids.get(world)
        if wid is None:
            wid = self._world_ids[world] = len(self._world_names)
            self._world_names.append(world)
        pid = self._peer_ids.get(sender)
        if pid is None:
            pid = self._peer_ids[sender] = len(self._peer_uuids)
            self._peer_uuids.append(sender)
            if pid >= self._peer_key_arr.shape[0]:
                out = np.zeros(
                    (self._peer_key_arr.shape[0] * 2, 16), np.uint8
                )
                out[: self._peer_key_arr.shape[0]] = self._peer_key_arr
                self._peer_key_arr = out
            self._peer_key_arr[pid] = np.frombuffer(sender.bytes, np.uint8)
        self._slot_of[uuid] = slot
        self._slot_of_key[uuid.bytes] = slot
        self._uuid_of[slot] = uuid
        self._uuid_bytes[slot] = np.frombuffer(uuid.bytes, np.uint8)
        self._wid[slot] = wid
        self._pid[slot] = pid
        self._vel[slot] = 0.0
        self._live[slot] = True
        # slot identity changed: cached frames keyed on row indices
        # could alias the new occupant — drop them all
        self._frame_cache.clear()
        self._peer_slots.setdefault(pid, set()).add(slot)
        # index coupling: a fresh entity is queryable IMMEDIATELY —
        # its row enters the index's delta path in this same turn.
        # The cube registers from the wire position below via the
        # same refcount transition churn uses.
        self._cube[slot] = 0  # filled by _register_cube after pos write
        return slot

    def _register_cube(self, slot: int) -> None:
        """Refcount-in the slot's CURRENT position cube (registration
        path; churn uses the vectorized transition in apply())."""
        cube = cube_coords_batch(
            self._pos[slot].astype(np.float64), self.cube_size
        )
        self._cube[slot] = cube
        self._ref_add(
            int(self._wid[slot]), cube, int(self._pid[slot]),
        )

    def _ref_key(self, wid: int, cube, pid: int) -> tuple:
        return (wid, int(cube[0]), int(cube[1]), int(cube[2]), pid)

    def _ref_add(self, wid: int, cube, pid: int) -> None:
        key = self._ref_key(wid, cube, pid)
        self._sub_refs[key] += 1
        if self._sub_refs[key] == 1:
            self.backend.add_subscription(
                self._world_names[wid], self._peer_uuids[pid],
                (int(cube[0]), int(cube[1]), int(cube[2])),
            )

    def _ref_drop(self, wid: int, cube, pid: int) -> None:
        key = self._ref_key(wid, cube, pid)
        self._sub_refs[key] -= 1
        if self._sub_refs[key] <= 0:
            del self._sub_refs[key]
            self.backend.remove_subscription(
                self._world_names[wid], self._peer_uuids[pid],
                (int(cube[0]), int(cube[1]), int(cube[2])),
            )

    def _remove_entity(self, uuid: uuid_mod.UUID,
                       sender: uuid_mod.UUID | None) -> int:
        slot = self._slot_of.get(uuid)
        if slot is None:
            return 0
        pid = int(self._pid[slot])
        if sender is not None and self._peer_uuids[pid] != sender:
            logger.warning(
                "peer %s sent remove for entity %s it does not own — "
                "dropped", sender, uuid,
            )
            return 0
        self._ref_drop(int(self._wid[slot]), self._cube[slot], pid)
        self._release_slot(slot, pid)
        return 1

    def _release_slot(self, slot: int, pid: int) -> None:
        if self._delta_ticks:
            # the vacated cube must dirty (its remaining residents'
            # neighborhoods change) and the slot's retained results
            # must blank — wid/cube wipe below loses both otherwise
            self._window_dirty_cubes.append((
                int(self._wid[slot]), int(self._cube[slot, 0]),
                int(self._cube[slot, 1]), int(self._cube[slot, 2]),
            ))
            self._window_dirty[slot] = False  # dead slots never compute
            if self._have_last:
                self._last_targets[slot] = -1
                self._last_counts[slot] = 0
        uuid = self._uuid_of.pop(slot)
        del self._slot_of[uuid]
        self._slot_of_key.pop(uuid.bytes, None)
        # a staged update must not resurrect a removed entity at the
        # flip: clear the slot's staging bits on both buffer sides
        for buf in self._stage:
            buf.touched[slot] = False
            buf.has_vel[slot] = False
        slots = self._peer_slots.get(pid)
        if slots is not None:
            slots.discard(slot)
            if not slots:
                del self._peer_slots[pid]
        self._live[slot] = False
        self._touched[slot] = False
        self._wid[slot] = -1
        self._pid[slot] = -1
        self._pos[slot] = _DEAD_POS
        self._vel[slot] = 0.0
        self._uuid_bytes[slot] = 0
        # the parked values must reach the device twin
        self._device_dirty[slot] = True
        self._free.append(slot)
        # slot identity changed (see _alloc_slot): cached frames over
        # this row are stale the moment the slot is reusable
        self._frame_cache.clear()
        self.entities_removed += 1

    def on_peer_removed(self, peer: uuid_mod.UUID) -> int:
        """Disconnect sweep: drop every entity the peer owned. The
        server purges the peer's index rows wholesale via
        ``backend.remove_peer`` BEFORE this hook runs, so only the
        plane-side bookkeeping (slots + refcounts) is released here."""
        pid = self._peer_ids.get(peer)
        if self.interest is not None:
            self.interest.forget_peer(peer)
        if pid is None:
            return 0
        removed = 0
        for slot in list(self._peer_slots.get(pid, ())):
            key = self._ref_key(
                int(self._wid[slot]), self._cube[slot], pid
            )
            self._sub_refs.pop(key, None)  # index row already purged
            self._release_slot(slot, pid)
            removed += 1
        return removed

    # region: world migration (live resharding)

    def export_world(self, world: str) -> list[dict]:
        """Snapshot every live entity of ``world`` as JSON-safe rows —
        the entity leg of a migration capsule. Ownership rides along
        (``owner`` hex): the new shard must enforce the same
        owner-only update rule the old one did."""
        wid = self._world_ids.get(world)
        if wid is None:
            return []
        rows = []
        for slot in np.flatnonzero(self._live & (self._wid == wid)):
            slot = int(slot)
            rows.append({
                "uuid": self._uuid_of[slot].hex,
                "owner": self._peer_uuids[int(self._pid[slot])].hex,
                "pos": [float(v) for v in self._pos[slot]],
                "vel": [float(v) for v in self._vel[slot]],
            })
        return rows

    def import_world(self, world: str, rows: list[dict]) -> int:
        """Replay exported entity rows into THIS plane through the
        normal registration path (``_upsert``), so index coupling,
        refcounts, and device-dirty tracking all engage exactly as a
        live registration would."""
        applied = 0
        for row in rows:
            try:
                ent = Entity(
                    uuid=uuid_mod.UUID(hex=row["uuid"]),
                    position=Vector3(*(float(v) for v in row["pos"])),
                    world_name=world,
                    flex=np.asarray(
                        row.get("vel") or (0.0, 0.0, 0.0), np.float32
                    ).tobytes(),
                )
                owner = uuid_mod.UUID(hex=row["owner"])
            except (KeyError, TypeError, ValueError):
                continue
            applied += self._upsert(ent, _WIRE_MSG, owner)
        return applied

    def remove_world(self, world: str) -> int:
        """Tombstone leg: drop every entity of ``world`` through the
        normal removal path (refcount transition included, so the
        backend index rows leave with the slots)."""
        wid = self._world_ids.get(world)
        if wid is None:
            return 0
        removed = 0
        for slot in np.flatnonzero(self._live & (self._wid == wid)):
            slot = int(slot)
            pid = int(self._pid[slot])
            self._ref_drop(wid, self._cube[slot], pid)
            self._release_slot(slot, pid)
            removed += 1
        return removed

    # endregion

    def _grow(self, cap: int) -> None:
        """Double the capacity tier (pow2): reallocate every column,
        preserving slots. The next dispatch compiles the new tier —
        visible in device.retraces as a tier first hit, exactly like
        the query engine's capacity ladder."""
        def grow2(arr, fill, dtype, width=None):
            shape = (cap,) if width is None else (cap, width)
            out = np.full(shape, fill, dtype)
            out[: self._cap] = arr
            return out

        self._pos = grow2(self._pos, _DEAD_POS, np.float32, 3)
        self._vel = grow2(self._vel, 0.0, np.float32, 3)
        self._wid = grow2(self._wid, -1, np.int32)
        self._pid = grow2(self._pid, -1, np.int32)
        self._cube = grow2(self._cube, 0, np.int64, 3)
        self._live = grow2(self._live, False, bool)
        self._touched = grow2(self._touched, False, bool)
        self._uuid_bytes = grow2(self._uuid_bytes, 0, np.uint8, 16)
        self._device_dirty = grow2(self._device_dirty, False, bool)
        self._window_dirty = grow2(self._window_dirty, False, bool)
        for buf in self._stage:
            buf.grow(cap)
        # shape change: the next dispatch re-ships the whole tier and
        # the retained last-tick arrays no longer fit — full recompute
        self._dev_state = None
        self._have_last = False
        self._cap = cap
        logger.info("entity plane grew to capacity tier %d", cap)

    # endregion

    # region: sim tick (ticker flush path)

    def _upload_state(self, cap: int) -> EntityState:
        """Device input for this tick: the persistent twin with only
        the DIRTY slots scattered in (incremental H2D), or a full-tier
        upload when there is no valid twin / the tier changed / the
        dirty set is dense enough that one straight re-ship wins."""
        dev = self._dev_state
        if dev is not None and self._dev_cap == cap:
            dirty = np.flatnonzero(self._device_dirty[:cap])
            if dirty.size == 0:
                self.last_h2d_rows = 0
                return dev
            if dirty.size <= cap // 2:
                try:
                    # entities.scatter: the incremental-H2D loss
                    # boundary — a scatter failure (or an armed chaos
                    # fault) degrades to one full-tier upload below,
                    # counted; the dirty bitmap is cleared only AFTER
                    # the scatter succeeds, so no row is ever lost to
                    # a failed partial transfer
                    failpoints.fire("entities.scatter")
                    bucket = max(
                        _SCATTER_MIN_BUCKET, _next_pow2(dirty.size)
                    )
                    # pad lanes carry the out-of-range index `cap`; the
                    # scatter drops them on device (mode='drop')
                    idx = np.full(bucket, cap, np.int32)
                    idx[: dirty.size] = dirty
                    rows = np.zeros((bucket, 3), np.float32)
                    rows_v = np.zeros((bucket, 3), np.float32)
                    rows_w = np.zeros(bucket, np.int32)
                    rows_p = np.zeros(bucket, np.int32)
                    rows[: dirty.size] = self._pos[dirty]
                    rows_v[: dirty.size] = self._vel[dirty]
                    rows_w[: dirty.size] = self._wid[dirty]
                    rows_p[: dirty.size] = self._pid[dirty]
                    out = self._scatter_fn(dev, idx, rows, rows_v,
                                           rows_w, rows_p)
                    self._device_dirty[:cap] = False
                    self.h2d_scatter += 1
                    self.last_h2d_rows = int(dirty.size)
                    return out
                except Exception:
                    self.scatter_fallbacks += 1
                    if self.metrics is not None:
                        self.metrics.inc("sim.scatter_fallbacks")
                    logger.exception(
                        "incremental H2D scatter failed (%d dirty "
                        "rows) — degrading to a full-tier upload",
                        int(dirty.size),
                    )
        self._device_dirty[:cap] = False
        self._dev_cap = cap
        self.h2d_full += 1
        self.last_h2d_rows = cap
        return EntityState(
            position=jnp.asarray(self._pos),
            velocity=jnp.asarray(self._vel),
            world=jnp.asarray(self._wid),
            peer=jnp.asarray(self._pid),
        )

    def dispatch_tick(self):
        """Launch one simulation tick from the host columns (event-loop
        thread; tick.sim.integrate span): fold the staged update
        columns, pick the delta or full path, launch the kernel (when
        any device work is owed), and enqueue the D2H prefetch.
        Returns an opaque handle for ``collect_tick`` or None when idle
        / a previous tick is still in flight (pipelined flushes never
        stack sim ticks — the writeback of tick N is input to tick
        N+1)."""
        self._drain_pending()  # staged updates fold tick-edge
        if not self._slot_of or self._tick_inflight:
            return None
        t0 = time.perf_counter()
        cap = self._cap
        handle = None
        if self._delta_ticks:
            handle = self._dispatch_tick_delta(cap, t0)
        if handle is None:
            # designated fallback: cold replay state, tier change, or
            # churn past the rebuild threshold — one full-tier tick
            # re-establishes the retained state delta ticks splice over
            handle = self._dispatch_tick_full(cap, t0)  # wql: allow(full-rebuild-on-tick)
        # window clearing happens only on a SUCCESSFUL launch: a
        # raising dispatch keeps every mark for the retry, and
        # abort_tick drops _have_last so dirt consumed by a tick that
        # never applied cannot leak a stale replay
        self._touched[:cap] = False
        self._window_dirty[:cap] = False
        self._window_dirty_cubes.clear()
        self._tick_inflight = True
        self.dispatches += 1
        self.last_integrate_ms = (time.perf_counter() - t0) * 1e3
        if self.metrics is not None:
            self.metrics.observe_ms("sim.integrate_ms", self.last_integrate_ms)
            self.metrics.inc("sim.h2d_rows", self.last_h2d_rows)
        return handle

    def _dispatch_tick_full(self, cap: int, t0: float) -> dict:
        """The pre-delta full path: ship dirty slots to the persistent
        twin, run the fused kernel over the WHOLE capacity tier."""
        state = self._upload_state(cap)
        new_state, targets, counts = self._tick_fn(state)
        # device twin for the NEXT tick: integrated positions; the
        # UPLOADED (host-authoritative) velocity — the in-tick bounce
        # reflection is per-tick, exactly as the full re-upload it
        # replaced behaved (apply() writes back positions only)
        self._dev_state = EntityState(
            position=new_state.position,
            velocity=state.velocity,
            world=state.world,
            peer=state.peer,
        )
        for arr in (new_state.position, targets, counts):
            copy_async = getattr(arr, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        self.full_sim_ticks += 1
        return {
            "mode": "full",
            "pos": new_state.position,
            "targets": targets,
            "counts": counts,
            "cap": cap,
            "t0": t0,
        }

    def _note_delta_fallback(self, reason: str) -> None:
        self.delta_fallbacks += 1
        self.last_delta_stats = {
            "reused": 0, "recomputed": 0, "dirty_cubes": 0,
            "fallback": reason,
        }
        if self.metrics is not None:
            self.metrics.inc("delta.sim_fallbacks")

    def _predict_cubes(self, slots: np.ndarray) -> np.ndarray:
        """Post-integration cubes of ``slots``, predicted host-side by
        replaying the device's f32 integrate+reflect bit-for-bit
        (numpy f32 add/mul/compare are the same IEEE ops XLA emits)
        and quantizing with the golden host quantizer — EXACT against
        the device labels for pow2 cube sizes (the plane's delta gate;
        tests/test_quantizer_envelope pins the agreement)."""
        dt = np.float32(self.dt)
        tb = np.float32(2.0 * self.bounds)  # the kernel's weak-f32 2*b
        b = np.float32(self.bounds)
        p = self._pos[slots] + self._vel[slots] * dt
        p = np.where(p > b, tb - p, p)
        p = np.where(p < -b, -tb - p, p)
        return cube_coords_batch(p.astype(np.float64), self.cube_size)

    def _dispatch_tick_delta(self, cap: int, t0: float) -> dict | None:
        """Delta path: build the dirty-cube closure and launch the
        tick kernel over ONLY it, at a pow2 sub-tier. Returns None to
        fall back to the full path (cold cache, tier change, or churn
        past ``delta_rebuild_threshold`` — the rebuild threshold)."""
        if not self._have_last or self._last_cap != cap:
            self._note_delta_fallback("cold")
            return None
        live = self._live[:cap]
        n_live = int(np.count_nonzero(live))
        moving = live & (self._vel[:cap] != 0.0).any(axis=1)
        dirty = (self._window_dirty[:cap] & live) | moving
        dirty_slots = np.flatnonzero(dirty)
        if dirty_slots.size == 0 and not self._window_dirty_cubes:
            # the world did not change: zero device work, pure replay
            self.delta_sim_ticks += 1
            self.delta_reused += n_live
            self.last_h2d_rows = 0
            self.last_delta_stats = {
                "reused": n_live, "recomputed": 0, "dirty_cubes": 0,
                "fallback": "",
            }
            return {"mode": "replay", "cap": cap, "t0": t0}
        threshold = self.delta_rebuild_threshold * max(n_live, 1)
        if dirty_slots.size > threshold:
            self._note_delta_fallback("churn")
            return None
        # dirty cubes: every cube a dirty entity occupies now or can
        # reach this tick, plus cubes vacated by removals
        wid_col = self._wid[:cap]
        cube_col = self._cube[:cap]
        parts = [spatial_keys(wid_col[dirty_slots],
                              cube_col[dirty_slots], 0)]
        if dirty_slots.size:
            parts.append(spatial_keys(
                wid_col[dirty_slots], self._predict_cubes(dirty_slots), 0
            ))
        if self._window_dirty_cubes:
            arr = np.asarray(self._window_dirty_cubes, np.int64)  # wql: allow(host-sync-in-sim-tick) — host tuple list, not a device array
            parts.append(spatial_keys(
                arr[:, 0].astype(np.int32), arr[:, 1:], 0
            ))
        dirty_keys = np.unique(np.concatenate(parts))
        # closure: every live entity in a dirty cube (a same-hash
        # collision only ADDS members — conservative, never wrong)
        closure = live & np.isin(
            spatial_keys(wid_col, cube_col, 0), dirty_keys
        )
        rows = np.flatnonzero(closure)
        tier = max(_DELTA_MIN_TIER, _next_pow2(max(int(rows.size), 1)))
        if rows.size > threshold or tier >= cap:
            self._note_delta_fallback("closure")
            return None
        # gather the closure into the sub-tier; pad lanes are parked
        # dead rows (peer -1 → the kernel masks them out of every run)
        pos_sub = np.full((tier, 3), _DEAD_POS, np.float32)
        vel_sub = np.zeros((tier, 3), np.float32)
        wid_sub = np.full(tier, -1, np.int32)
        pid_sub = np.full(tier, -1, np.int32)
        n = int(rows.size)
        pos_sub[:n] = self._pos[rows]
        vel_sub[:n] = self._vel[rows]
        wid_sub[:n] = wid_col[rows]
        pid_sub[:n] = self._pid[rows]
        state = EntityState(
            position=jnp.asarray(pos_sub), velocity=jnp.asarray(vel_sub),
            world=jnp.asarray(wid_sub), peer=jnp.asarray(pid_sub),
        )
        new_state, targets, counts = self._tick_fn(state)
        for arr in (new_state.position, targets, counts):
            copy_async = getattr(arr, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        self.delta_sim_ticks += 1
        self.delta_reused += n_live - n
        self.delta_recomputed += n
        self.last_h2d_rows = n
        self.last_delta_stats = {
            "reused": n_live - n, "recomputed": n,
            "dirty_cubes": int(dirty_keys.size), "fallback": "",
        }
        return {
            "mode": "delta",
            "rows": rows,
            "dirty_keys": dirty_keys,
            "pos": new_state.position,
            "targets": targets,
            "counts": counts,
            "cap": cap,
            "tier": tier,
            "t0": t0,
        }

    def precompile(self, max_compiles: int = 32) -> dict:
        """Boot-time shape precompilation for the sim kernels (the
        PR 8 tier-precompile discipline extended to the entity plane):
        the tick kernel at the current capacity tier plus the
        incremental-H2D scatter across its pow2 dirty-bucket ladder, so
        steady-state serving re-traces nothing. Returns a stats dict in
        the spatial/precompile.py shape."""
        t0 = time.perf_counter()
        before = GUARD.counts()
        cap = self._cap
        compiles = skipped = 0
        zeros3 = jnp.zeros((cap, 3), jnp.float32)
        ids = jnp.full(cap, -1, jnp.int32)
        state = EntityState(zeros3, zeros3, ids, ids)
        out = self._tick_fn(state)
        jax.block_until_ready(out)
        compiles += 1
        bucket = _SCATTER_MIN_BUCKET
        while bucket <= cap:
            if compiles >= max(1, int(max_compiles)):
                skipped += 1
                bucket *= 2
                continue
            idx = np.full(bucket, cap, np.int32)
            state = self._scatter_fn(
                state, idx,
                np.zeros((bucket, 3), np.float32),
                np.zeros((bucket, 3), np.float32),
                np.zeros(bucket, np.int32),
                np.zeros(bucket, np.int32),
            )
            compiles += 1
            bucket *= 2
        if self._delta_ticks:
            # delta-tick sub-batch ladder: the dirty-closure kernel is
            # the SAME tick fn at smaller pow2 tiers — walk them so a
            # low-churn steady state re-traces nothing mid-serving
            tier = _DELTA_MIN_TIER
            while tier < cap:
                if compiles >= max(1, int(max_compiles)):
                    skipped += 1
                    tier *= 2
                    continue
                z3 = jnp.zeros((tier, 3), jnp.float32)
                neg = jnp.full(tier, -1, jnp.int32)
                out = self._tick_fn(EntityState(z3, z3, neg, neg))
                jax.block_until_ready(out)
                compiles += 1
                tier *= 2
        jax.block_until_ready(state)
        delta = GUARD.delta(before)
        stats = {
            "dispatches": compiles,
            "skipped_by_budget": skipped,
            "new_variants": sum(delta.values()),
            "families": delta,
            "wall_ms": round((time.perf_counter() - t0) * 1e3, 1),
        }
        logger.info(
            "entity tier precompilation: %d shapes walked, %d new "
            "kernel variants in %.0f ms",
            compiles, stats["new_variants"], stats["wall_ms"],
        )
        return stats

    def collect_tick(self, handle) -> dict:
        """Wait out the device and fetch results (worker thread;
        tick.sim.knn span). The three fetches below are the sim tick's
        designated device→host sync points; everything else stays
        vectorized. Also re-quantizes the integrated positions to
        cubes host-side in f64 — the AUTHORITATIVE quantizer, so the
        index coupling follows the golden grid, not the device's f32
        twin."""
        t0 = time.perf_counter()
        mode = handle.get("mode", "full")
        if mode == "replay":
            # nothing was dispatched: the retained tick IS the result
            return {"mode": "replay", "cap": handle["cap"], "knn_ms": 0.0}
        pos = np.asarray(handle["pos"])  # wql: allow(host-sync-in-sim-tick) — designated collect point
        targets = np.asarray(handle["targets"])  # wql: allow(host-sync-in-sim-tick) — designated collect point
        counts = np.asarray(handle["counts"])  # wql: allow(host-sync-in-sim-tick) — designated collect point
        cubes = cube_coords_batch(pos.astype(np.float64), self.cube_size)
        knn_ms = (time.perf_counter() - t0) * 1e3
        out = {
            "mode": mode,
            "pos": pos, "targets": targets, "counts": counts,
            "cubes": cubes, "cap": handle["cap"], "knn_ms": knn_ms,
        }
        if mode == "delta":
            out["rows"] = handle["rows"]
            out["dirty_keys"] = handle["dirty_keys"]
        return out

    def abort_tick(self) -> None:
        """Drop an in-flight tick without applying it (cancelled or
        errored flush, or a resilience rebuild/failover swapping the
        backing index): host columns stay authoritative and unchanged,
        the next dispatch simply re-integrates from them. The device
        twin already holds the dropped tick's integration, so it is
        invalidated — the next dispatch re-ships the host tier. The
        delta-tick replay state drops with it: the aborted dispatch
        consumed the dirty window without ever applying, so the next
        tick must recompute the world in full."""
        self._have_last = False
        if self._tick_inflight:
            self._tick_inflight = False
            self._dev_state = None
            self.dropped_ticks += 1

    def apply(self, result: dict, trace=None,
              skip_frames: bool = False) -> list:
        """Integrate one collected tick back into the host authority
        (event-loop thread): position writeback, index churn through
        the base+delta path, neighbor-frame assembly. Returns
        ``(message, targets)`` delivery pairs for the tick's batched
        deliver. ``skip_frames`` (tick-deadline degradation) applies
        the writeback + churn but sheds the frame leg — counted, never
        silent."""
        self._tick_inflight = False
        t0 = time.perf_counter()
        cap = result["cap"]
        mode = result.get("mode", "full")
        if mode == "replay":
            # nothing changed since the retained tick: positions,
            # cubes and the index are already exactly what a full
            # recompute would produce — only the frame leg runs
            moved_slots = np.empty(0, np.intp)
            pos = self._last_pos
            targets, counts = self._last_targets, self._last_counts
        elif mode == "delta":
            pos, targets, counts, moved_slots = self._apply_delta(result)
        else:
            pos, cubes = result["pos"], result["cubes"]
            targets, counts = result["targets"], result["counts"]

            # 1. position writeback — every live slot that the wire
            # did NOT touch since dispatch (a client update must win
            # over the concurrent integration it never saw)
            wb = self._live[:cap] & ~self._touched[:cap]
            self._pos[:cap][wb] = pos[wb]

            # 2. index churn: slots whose authoritative cube moved.
            # Only written-back slots move here — touched slots
            # re-quantize at the NEXT applied tick from their
            # client-given position.
            moved = wb & np.any(cubes != self._cube[:cap], axis=1)
            moved_slots = np.flatnonzero(moved)
            if moved_slots.size:
                self._apply_churn(moved_slots, cubes[moved_slots])
            # retain this tick as the delta replay source — as
            # WRITABLE copies: np.asarray of a device buffer is a
            # read-only zero-copy view, and delta ticks splice their
            # sub-results into these in place
            if self._delta_ticks:
                self._last_pos = np.array(pos)
                self._last_targets = np.array(targets)
                self._last_counts = np.array(counts)
                self._have_last = True
                self._last_cap = cap
        self.last_churn = int(moved_slots.size)

        # 3. neighbor frames: one message per entity with >= 1 target,
        # fanned out to the owning peers of its k nearest co-cube
        # entities (the device already applied except-self per PEER)
        if skip_frames:
            pairs = []
            self.frames_skipped += 1
            if self.metrics is not None:
                self.metrics.inc("sim.frames_skipped")
        elif self.interest is not None:
            pairs = self.interest.build_pairs(self, pos, targets, cap)
        else:
            pairs = self._build_frames(pos, targets, counts, cap)

        self.applied_ticks += 1
        self.frames += len(pairs)
        self.last_apply_ms = (time.perf_counter() - t0) * 1e3
        self.last_knn_ms = result["knn_ms"]
        if self.metrics is not None:
            self.metrics.observe_ms("sim.knn_ms", result["knn_ms"])
            self.metrics.observe_ms("sim.apply_ms", self.last_apply_ms)
            if moved_slots.size:
                self.metrics.inc("sim.index_moves", int(moved_slots.size))
            if pairs:
                self.metrics.inc("sim.frames", len(pairs))
            if self._delta_ticks and self.last_delta_stats:
                self.metrics.inc(
                    "delta.sim_reused", self.last_delta_stats["reused"]
                )
                self.metrics.inc(
                    "delta.sim_recomputed",
                    self.last_delta_stats["recomputed"],
                )
        if trace is not None:
            tags = {
                "entities": len(self._slot_of),
                "frames": len(pairs),
                "index_moves": int(moved_slots.size),
                "integrate_ms": round(self.last_integrate_ms, 3),
                "knn_ms": round(result["knn_ms"], 3),
                "apply_ms": round(self.last_apply_ms, 3),
            }
            if self._delta_ticks:
                tags["delta"] = dict(self.last_delta_stats)
            trace.tag(sim=tags)
        return pairs

    def _apply_delta(self, result: dict):
        """Splice a delta sub-tick over the retained last-tick arrays:
        closure rows take the freshly computed values, clean rows keep
        (replay) theirs. Returns ``(pos, targets, counts,
        moved_slots)`` for the shared apply tail — ``pos`` is the
        device-integrated frame position column, exactly what the full
        path hands it."""
        rows = result["rows"]
        n = int(rows.size)
        pos_sub = result["pos"][:n]
        cubes_sub = result["cubes"][:n]
        self._last_targets[rows] = result["targets"][:n]
        self._last_counts[rows] = result["counts"][:n]
        self._last_pos[rows] = pos_sub

        # writeback + churn for closure rows the wire didn't touch
        # mid-flight (same mask the full path applies tier-wide);
        # rows removed mid-flight dropped out of `live` already
        wb = self._live[rows] & ~self._touched[rows]
        wrows = rows[wb]
        self._pos[wrows] = pos_sub[wb]
        moved = np.any(cubes_sub[wb] != self._cube[wrows], axis=1)
        moved_slots = wrows[moved]
        if moved_slots.size:
            self._apply_churn(moved_slots, cubes_sub[wb][moved])

        # defensive closure audit: every written-back row must land in
        # a cube the dispatch predicted dirty — unreachable inside the
        # pinned quantizer envelope, but a mispredict would mean some
        # clean cube replayed stale neighbors, so it forces the next
        # tick onto the full path instead of trusting the replay state
        if moved_slots.size:
            landed = spatial_keys(
                self._wid[moved_slots], cubes_sub[wb][moved], 0
            )
            bad = int(np.count_nonzero(
                ~np.isin(landed, result["dirty_keys"])
            ))
            if bad:
                self.delta_mispredicts += bad
                self._have_last = False
                logger.warning(
                    "delta tick mispredicted %d cube landings — "
                    "forcing a full recompute next tick", bad,
                )

        # the device twin never saw this sub-tick: closure rows are
        # stale there until the next full-path scatter re-ships them
        self._device_dirty[wrows] = True
        return self._last_pos, self._last_targets, self._last_counts, \
            moved_slots

    def _apply_churn(self, moved_slots: np.ndarray,
                     new_cubes: np.ndarray) -> None:
        """Move the index rows of slots whose cube changed, through the
        backend's delta path. ``new_cubes`` are the moved slots' fresh
        cubes, row-aligned with ``moved_slots``. Refcount transitions
        decide which moves actually touch the index (co-located
        entities of one peer share a row); the surviving adds/removes
        go down vectorized, grouped by world, via
        ``bulk_move_subscriptions`` when the backend has it
        (TPU/sharded) or per-row mutations otherwise."""
        old_cubes = self._cube[moved_slots].copy()
        wids = self._wid[moved_slots]
        pids = self._pid[moved_slots]
        self._cube[moved_slots] = new_cubes
        self.index_moves += int(moved_slots.size)

        # refcount transitions (O(churn) host work, like any index
        # mutation batch): rows crossing 0 materialize as index ops
        add_rows: list[int] = []
        rem_rows: list[int] = []
        refs = self._sub_refs
        for i in range(moved_slots.size):
            wid = int(wids[i])
            pid = int(pids[i])
            old_key = (wid, int(old_cubes[i, 0]), int(old_cubes[i, 1]),
                       int(old_cubes[i, 2]), pid)
            new_key = (wid, int(new_cubes[i, 0]), int(new_cubes[i, 1]),
                       int(new_cubes[i, 2]), pid)
            refs[old_key] -= 1
            if refs[old_key] <= 0:
                del refs[old_key]
                rem_rows.append(i)
            refs[new_key] += 1
            if refs[new_key] == 1:
                add_rows.append(i)

        bulk_move = getattr(self.backend, "bulk_move_subscriptions", None)
        for wid in np.unique(wids).tolist():
            world = self._world_names[wid]
            rem = [i for i in rem_rows if wids[i] == wid]
            add = [i for i in add_rows if wids[i] == wid]
            rem_peers = [self._peer_uuids[int(pids[i])] for i in rem]
            add_peers = [self._peer_uuids[int(pids[i])] for i in add]
            if bulk_move is not None:
                bulk_move(
                    world,
                    rem_peers, old_cubes[rem],
                    add_peers, new_cubes[add],
                )
            else:
                for peer, cube in zip(rem_peers, old_cubes[rem]):
                    self.backend.remove_subscription(
                        world, peer, tuple(int(c) for c in cube)
                    )
                for peer, cube in zip(add_peers, new_cubes[add]):
                    self.backend.add_subscription(
                        world, peer, tuple(int(c) for c in cube)
                    )
        # Make the churn visible to the device twin and run the LSM
        # compaction policy NOW: the query path calls flush() at every
        # dispatch, but an entity-sim-only server has no query
        # dispatches — without this the delta log (and its tombstones)
        # would grow without bound. No-op-cheap when nothing is dirty.
        self.backend.flush()

    def _build_frames(self, pos, targets, counts, cap: int) -> list:
        """Assemble per-entity neighbor frames: for every live entity
        with at least one resolved target, one ``entity.frame``
        LocalMessage carrying the entity's integrated position,
        addressed to the owning peers of its nearest neighbors.
        Entities sharing a (world, recipients) cohort encode in ONE
        native pass (serialize-once per cohort) and hand ready wire
        bytes to deliver_batch — zero per-entity Message objects; the
        object path below is the fallback for a stale native library.
        O(entities with neighbors) host work either way — the
        delivery-path analog of the query engine's decode."""
        live = self._live[:cap]
        valid = targets >= 0
        has_any = live & valid.any(axis=1)
        rows = np.flatnonzero(has_any)
        if rows.size == 0:
            return []
        wire = self._wire
        if wire is None or not wire.can_encode_frames:
            return self._build_frames_py(pos, targets, valid, rows)
        # cohort key = (world, sorted target lanes): rows agreeing on
        # both share one recipient list and one native encode pass
        tr = np.sort(targets[rows], axis=1)
        key = np.concatenate(
            [self._wid[rows][:, None], tr.astype(np.int32)], axis=1
        )
        cohorts, inverse = np.unique(key, axis=0, return_inverse=True)
        pairs = []
        peer_uuids = self._peer_uuids
        cache = self._frame_cache
        next_cache: dict[bytes, tuple] = {}
        reused = 0
        for c in range(cohorts.shape[0]):
            crows = rows[inverse == c]
            # frame-level reuse: the cohort key pins world + recipient
            # set; byte-identical member rows and positions pin the
            # encoded output exactly (sender keys and entity uuids are
            # per-slot constants within a roster epoch — any slot
            # alloc/release cleared the cache), so a clean cohort
            # replays last tick's wire bytes, parity byte for byte
            key_b = cohorts[c].tobytes()
            crows_b = crows.tobytes()
            sub_pos = pos[crows]
            pos_b = sub_pos.tobytes()
            cached = cache.get(key_b)
            if (
                cached is not None
                and cached[0] == crows_b
                and cached[1] == pos_b
            ):
                frames, targets_u = cached[2], cached[3]
                reused += len(frames)
            else:
                tgt = cohorts[c, 1:]
                tgt = np.unique(tgt[tgt >= 0])
                targets_u = [peer_uuids[int(p)] for p in tgt]
                world = self._world_names[int(cohorts[c, 0])]
                frames = wire.encode_frames(
                    self._peer_key_arr[self._pid[crows]],
                    self._uuid_bytes[crows],
                    sub_pos.astype(np.float64),
                    world.encode(),
                )
            next_cache[key_b] = (crows_b, pos_b, frames, targets_u)
            pairs.extend((WireFrame(f), targets_u) for f in frames)
        # cohorts absent this tick age out with the wholesale swap
        self._frame_cache = next_cache
        if reused:
            self.frames_reused += reused
            if self.metrics is not None:
                self.metrics.inc("delta.frames_reused", reused)
        self.frames_native += len(pairs)
        return pairs

    def _build_frames_py(self, pos, targets, valid, rows) -> list:
        """Object-path frame assembly (stale-native fallback): one
        Message per entity, serialized later by deliver_batch."""
        pairs = []
        peer_uuids = self._peer_uuids
        uuid_of = self._uuid_of
        world_names = self._world_names
        wid_col = self._wid
        pid_col = self._pid
        for row in rows.tolist():
            tgt_pids = np.unique(targets[row][valid[row]])
            targets_u = [peer_uuids[int(p)] for p in tgt_pids]
            position = Vector3(
                float(pos[row, 0]), float(pos[row, 1]), float(pos[row, 2])
            )
            world = world_names[int(wid_col[row])]
            msg = Message(
                instruction=Instruction.LOCAL_MESSAGE,
                parameter=PARAM_FRAME,
                sender_uuid=peer_uuids[int(pid_col[row])],
                world_name=world,
                position=position,
                entities=[Entity(
                    uuid=uuid_of[row], position=position,
                    world_name=world,
                )],
            )
            pairs.append((msg, targets_u))
        return pairs

    # endregion

    def stats(self) -> dict:
        return {
            "entities": len(self._slot_of),
            "capacity": self._cap,
            "peers": len(self._peer_slots),
            "worlds": len(self._world_names),
            "k": self.k,
            "registered": self.entities_registered,
            "removed": self.entities_removed,
            "updates": self.updates,
            "rejected": self.rejected,
            "dispatches": self.dispatches,
            "applied_ticks": self.applied_ticks,
            "dropped_ticks": self.dropped_ticks,
            "frames": self.frames,
            "frames_skipped": self.frames_skipped,
            "frames_native": self.frames_native,
            "frames_reused": self.frames_reused,
            "coalesced": self.coalesced,
            "pending": self.staged_count(),
            "wire_rows": self.wire_rows,
            "wire_slow_rows": self.wire_slow_rows,
            "column_flips": self.column_flips,
            "h2d_full": self.h2d_full,
            "h2d_scatter": self.h2d_scatter,
            "scatter_fallbacks": self.scatter_fallbacks,
            "last_h2d_rows": self.last_h2d_rows,
            "index_moves": self.index_moves,
            "index_rows": len(self._sub_refs),
            "delta_ticks": self._delta_ticks,
            "delta_sim_ticks": self.delta_sim_ticks,
            "full_sim_ticks": self.full_sim_ticks,
            "delta_reused": self.delta_reused,
            "delta_recomputed": self.delta_recomputed,
            "delta_fallbacks": self.delta_fallbacks,
            "delta_mispredicts": self.delta_mispredicts,
            "last_integrate_ms": round(self.last_integrate_ms, 3),
            "last_knn_ms": round(self.last_knn_ms, 3),
            "last_apply_ms": round(self.last_apply_ms, 3),
            "last_churn": self.last_churn,
        }


def _decode_velocity(flex: bytes | None):
    """Wire velocity: ``Entity.flex`` carries 12 little-endian f32
    bytes (vx, vy, vz). Absent/short flex = no velocity change (zero
    for a fresh registration)."""
    if flex is None or len(flex) < 12:
        return None
    return np.frombuffer(flex[:12], dtype="<f4").astype(np.float32)
