"""EntityPlane: the device-resident moving-object workload.

One plane owns every live entity as a slot in preallocated host SoA
columns (``pos f32[cap,3] | vel f32[cap,3] | wid i32 | pid i32``) plus
their device twin, an :class:`~worldql_server_tpu.ops.tick.EntityState`.
The host columns are the authority (the same discipline as
spatial/tpu_backend.py): wire ingest mutates them at message-arrival
time, each ticker flush uploads them whole, runs ONE jitted
``simulation_tick`` (integrate → re-quantize → spatial-hash rebuild →
stencil kNN, ops/tick.py), and the collect fetches back integrated
positions + per-entity neighbor targets.

Capacity is a power-of-two tier (``_MIN_CAP`` floor), so the jitted
tick sees a handful of shapes over a process lifetime — the tick
kernel registers with the retrace GUARD under ``entities.sim_tick``
and the e2e suite holds the steady-state budget.

Index coupling (the bounded-staleness contract): every entity also
owns ONE subscription row in the authoritative spatial index — its
owner peer subscribed at the entity's current cube — refcounted per
``(world, cube, peer)`` so co-located entities of one peer share a
row. Registration inserts the row IMMEDIATELY (a new entity is
queryable before its first tick); position churn flows through the
index's base+delta path (``bulk_move_subscriptions``) when the tick's
integrated position crosses a cube boundary. Subscription queries
therefore observe an entity's position with staleness bounded by ONE
applied tick: the cube registered in the index is the quantization of
the position the LAST applied tick integrated (plus any not-yet-ticked
wire update, which re-quantizes at the next apply). Entity state and
index can never diverge structurally — both are derived from the same
host columns, and the index mutation happens in the same event-loop
turn as the position writeback.

Tick-path discipline: ``dispatch_tick``/``collect_tick`` are the
sim-tick hot functions — no per-entity Python, host syncs only at the
designated collect points (tools/check: host-sync-in-sim-tick). Frame
assembly and index churn (``apply``) are host delivery/index work,
O(fan-out) and O(churn) respectively, and run on the event loop like
the router's per-message handling.
"""

from __future__ import annotations

import logging
import time
import uuid as uuid_mod
from collections import Counter

import numpy as np

from ..spatial import jaxconf  # noqa: F401  (must precede jax import)
import jax
import jax.numpy as jnp

from ..ops.tick import EntityState, make_tick_fn
from ..protocol.types import Entity, Instruction, Message, Vector3
from ..spatial.quantize import cube_coords_batch
from ..utils.names import SanitizeError, sanitize_world_name
from ..utils.retrace import GUARD

logger = logging.getLogger(__name__)

#: Message.parameter marking an entity-removal batch (any other
#: parameter — usually None — upserts the carried entities)
PARAM_REMOVE = "entity.remove"
#: Message.parameter stamped on outbound neighbor frames
PARAM_FRAME = "entity.frame"

#: smallest capacity tier (pow2); arrays never shrink below it
_MIN_CAP = 256
#: parked coordinate for dead slots: quantizes to the saturated cube of
#: the dead world (wid -1), far outside any live neighborhood
_DEAD_POS = np.float32(1.0e30)


def _next_pow2(n: int) -> int:
    return 1 << (max(n, 1) - 1).bit_length()


class EntityPlane:
    """Device-resident entity population + its authoritative-index
    coupling for one server. Event-loop owned except where noted."""

    def __init__(
        self,
        backend,
        peer_map,
        *,
        cube_size: int,
        k: int = 8,
        dt: float = 0.05,
        bounds: float = 1000.0,
        max_entities: int = 1 << 16,
        metrics=None,
        tracer=None,
        governor=None,
    ):
        self.backend = backend
        self.peer_map = peer_map
        self.cube_size = cube_size
        self.k = int(k)
        self.dt = float(dt)
        self.bounds = float(bounds)
        self.max_entities = int(max_entities)
        self.metrics = metrics
        self.tracer = tracer
        # Optional robustness.overload.OverloadGovernor: under
        # SHED_LOW+ updates of LIVE entities coalesce last-write-wins
        # per uuid into _pending and apply once per tick — lossless
        # for position streams (the newest position subsumes the ones
        # it overwrote), and the first step of the columnar
        # entity-update staging path (ROADMAP item 4). Registrations
        # and removals always apply immediately (control plane).
        self._governor = governor
        #: uuid → latest staged Entity (bounded by live entities)
        self._pending: dict[uuid_mod.UUID, Entity] = {}
        self.coalesced = 0
        self.frames_skipped = 0

        # host SoA columns (authority; slot-indexed, pow2 capacity)
        self._cap = _MIN_CAP
        self._pos = np.full((self._cap, 3), _DEAD_POS, np.float32)
        self._vel = np.zeros((self._cap, 3), np.float32)
        self._wid = np.full(self._cap, -1, np.int32)
        self._pid = np.full(self._cap, -1, np.int32)
        #: cube currently registered in the authoritative index
        self._cube = np.zeros((self._cap, 3), np.int64)
        self._live = np.zeros(self._cap, bool)
        #: slots mutated by wire ingest since the LAST dispatch — the
        #: post-tick position writeback must not clobber them
        self._touched = np.zeros(self._cap, bool)

        self._n = 0                     # slot high-water mark
        self._free: list[int] = []      # recycled slots below _n
        self._slot_of: dict[uuid_mod.UUID, int] = {}
        self._uuid_of: dict[int, uuid_mod.UUID] = {}

        # interning (plane-local dense ids; the INDEX interns its own)
        self._world_ids: dict[str, int] = {}
        self._world_names: list[str] = []
        self._peer_ids: dict[uuid_mod.UUID, int] = {}
        self._peer_uuids: list[uuid_mod.UUID] = []
        #: per-peer entity slots (eviction sweep)
        self._peer_slots: dict[int, set[int]] = {}

        #: (wid, cx, cy, cz, pid) → live-entity refcount backing ONE
        #: index row; transitions through 0 mutate the index
        self._sub_refs: Counter = Counter()

        # one jitted tick fn; shape (= capacity tier) keys its compile
        # cache, which the retrace GUARD audits under entities.sim_tick
        self._tick_fn = jax.jit(
            make_tick_fn(
                cube_size=cube_size, k=self.k, dt=self.dt,
                bounds=self.bounds,
            )
        )
        GUARD.register("entities.sim_tick", self._tick_fn)
        self._tick_inflight = False

        # stats (exposed via the entity_sim gauge + bench config 8)
        self.entities_registered = 0
        self.entities_removed = 0
        self.updates = 0
        self.rejected = 0
        self.dispatches = 0
        self.applied_ticks = 0
        self.dropped_ticks = 0
        self.frames = 0
        self.index_moves = 0
        self.last_integrate_ms = 0.0
        self.last_knn_ms = 0.0
        self.last_apply_ms = 0.0
        self.last_churn = 0

    # region: wire ingest (router arrival path)

    @property
    def entity_count(self) -> int:
        return len(self._slot_of)

    def active(self) -> bool:
        return bool(self._slot_of)

    def ingest(self, message: Message) -> int:
        """Apply one inbound entity batch: upsert every carried Entity
        (or remove, when ``parameter == 'entity.remove'``) for the
        sending peer. Per-entity Python is fine HERE — this is the
        message-arrival path, amortized like any router handler.
        Returns entities applied."""
        sender = message.sender_uuid
        removing = message.parameter == PARAM_REMOVE
        governor = self._governor
        coalesce = (
            not removing
            and governor is not None
            and governor.coalesce_entities()
        )
        applied = 0
        for ent in message.entities:
            try:
                if removing:
                    applied += self._remove_entity(ent.uuid, sender)
                elif coalesce and ent.uuid in self._slot_of:
                    applied += self._stage_update(ent, message, sender)
                else:
                    applied += self._upsert(ent, message, sender)
            except SanitizeError as exc:
                logger.warning(
                    "peer %s sent entity with invalid world %r (%s)",
                    sender, ent.world_name or message.world_name, exc,
                )
        if applied and self.metrics is not None:
            self.metrics.inc("sim.updates", applied)
        self.updates += applied
        return applied

    def _stage_update(self, ent: Entity, message: Message,
                      sender: uuid_mod.UUID) -> int:
        """Coalescing admission (governor SHED_LOW+): stage the update
        of a LIVE entity last-write-wins per uuid; ``_drain_pending``
        applies the survivors in one pass at the next dispatch.
        Ownership and world sanitation are enforced HERE so a hostile
        update can't hide in the staging dict. An overwrite counts as
        ``overload.coalesced`` — shed-but-lossless work (the audit
        invariant: offered == applied + coalesced + dropped)."""
        sanitize_world_name(ent.world_name or message.world_name)
        slot = self._slot_of[ent.uuid]
        owner = self._peer_uuids[self._pid[slot]]
        if owner != sender:
            logger.warning(
                "peer %s sent update for entity %s owned by %s — "
                "dropped", sender, ent.uuid, owner,
            )
            return 0
        if ent.uuid in self._pending:
            self.coalesced += 1
            if self.metrics is not None:
                self.metrics.inc("overload.coalesced")
            self._pending[ent.uuid] = ent
            return 0
        self._pending[ent.uuid] = ent
        return 1

    def _drain_pending(self) -> None:
        """Apply every staged update straight into the host columns
        (one dict pass per tick instead of per-message work — the
        coalescing staleness bound is therefore the same one tick the
        plane already documents)."""
        if not self._pending:
            return
        pending, self._pending = self._pending, {}
        for eid, ent in pending.items():
            slot = self._slot_of.get(eid)
            if slot is None:
                continue  # removed after staging
            p = ent.position
            self._pos[slot, 0] = p.x
            self._pos[slot, 1] = p.y
            self._pos[slot, 2] = p.z
            vel = _decode_velocity(ent.flex)
            if vel is not None:
                self._vel[slot] = vel
            self._touched[slot] = True

    def _upsert(self, ent: Entity, message: Message,
                sender: uuid_mod.UUID) -> int:
        world = sanitize_world_name(ent.world_name or message.world_name)
        slot = self._slot_of.get(ent.uuid)
        new = slot is None
        if new:
            if len(self._slot_of) >= self.max_entities:
                self.rejected += 1
                if self.metrics is not None:
                    self.metrics.inc("sim.rejected")
                logger.warning(
                    "entity registration rejected: plane full "
                    "(%d >= max_entities %d)",
                    len(self._slot_of), self.max_entities,
                )
                return 0
            slot = self._alloc_slot(ent.uuid, sender, world)
            self.entities_registered += 1
        else:
            owner = self._peer_uuids[self._pid[slot]]
            if owner != sender:
                # an entity belongs to the peer that registered it;
                # a hijacking update is dropped, not transferred
                logger.warning(
                    "peer %s sent update for entity %s owned by %s — "
                    "dropped", sender, ent.uuid, owner,
                )
                return 0
        p = ent.position
        self._pos[slot, 0] = p.x
        self._pos[slot, 1] = p.y
        self._pos[slot, 2] = p.z
        vel = _decode_velocity(ent.flex)
        if vel is not None:
            self._vel[slot] = vel
        self._touched[slot] = True
        if new:
            # index coupling: queryable before the first tick
            self._register_cube(slot)
        return 1

    def _alloc_slot(self, uuid: uuid_mod.UUID, sender: uuid_mod.UUID,
                    world: str) -> int:
        if self._free:
            slot = self._free.pop()
        else:
            if self._n == self._cap:
                self._grow(self._cap * 2)
            slot = self._n
            self._n += 1
        wid = self._world_ids.get(world)
        if wid is None:
            wid = self._world_ids[world] = len(self._world_names)
            self._world_names.append(world)
        pid = self._peer_ids.get(sender)
        if pid is None:
            pid = self._peer_ids[sender] = len(self._peer_uuids)
            self._peer_uuids.append(sender)
        self._slot_of[uuid] = slot
        self._uuid_of[slot] = uuid
        self._wid[slot] = wid
        self._pid[slot] = pid
        self._vel[slot] = 0.0
        self._live[slot] = True
        self._peer_slots.setdefault(pid, set()).add(slot)
        # index coupling: a fresh entity is queryable IMMEDIATELY —
        # its row enters the index's delta path in this same turn.
        # The cube registers from the wire position below via the
        # same refcount transition churn uses.
        self._cube[slot] = 0  # filled by _register_cube after pos write
        return slot

    def _register_cube(self, slot: int) -> None:
        """Refcount-in the slot's CURRENT position cube (registration
        path; churn uses the vectorized transition in apply())."""
        cube = cube_coords_batch(
            self._pos[slot].astype(np.float64), self.cube_size
        )
        self._cube[slot] = cube
        self._ref_add(
            int(self._wid[slot]), cube, int(self._pid[slot]),
        )

    def _ref_key(self, wid: int, cube, pid: int) -> tuple:
        return (wid, int(cube[0]), int(cube[1]), int(cube[2]), pid)

    def _ref_add(self, wid: int, cube, pid: int) -> None:
        key = self._ref_key(wid, cube, pid)
        self._sub_refs[key] += 1
        if self._sub_refs[key] == 1:
            self.backend.add_subscription(
                self._world_names[wid], self._peer_uuids[pid],
                (int(cube[0]), int(cube[1]), int(cube[2])),
            )

    def _ref_drop(self, wid: int, cube, pid: int) -> None:
        key = self._ref_key(wid, cube, pid)
        self._sub_refs[key] -= 1
        if self._sub_refs[key] <= 0:
            del self._sub_refs[key]
            self.backend.remove_subscription(
                self._world_names[wid], self._peer_uuids[pid],
                (int(cube[0]), int(cube[1]), int(cube[2])),
            )

    def _remove_entity(self, uuid: uuid_mod.UUID,
                       sender: uuid_mod.UUID | None) -> int:
        slot = self._slot_of.get(uuid)
        if slot is None:
            return 0
        pid = int(self._pid[slot])
        if sender is not None and self._peer_uuids[pid] != sender:
            logger.warning(
                "peer %s sent remove for entity %s it does not own — "
                "dropped", sender, uuid,
            )
            return 0
        self._ref_drop(int(self._wid[slot]), self._cube[slot], pid)
        self._release_slot(slot, pid)
        return 1

    def _release_slot(self, slot: int, pid: int) -> None:
        uuid = self._uuid_of.pop(slot)
        del self._slot_of[uuid]
        # a staged update must not resurrect a removed entity at drain
        self._pending.pop(uuid, None)
        slots = self._peer_slots.get(pid)
        if slots is not None:
            slots.discard(slot)
            if not slots:
                del self._peer_slots[pid]
        self._live[slot] = False
        self._touched[slot] = False
        self._wid[slot] = -1
        self._pid[slot] = -1
        self._pos[slot] = _DEAD_POS
        self._vel[slot] = 0.0
        self._free.append(slot)
        self.entities_removed += 1

    def on_peer_removed(self, peer: uuid_mod.UUID) -> int:
        """Disconnect sweep: drop every entity the peer owned. The
        server purges the peer's index rows wholesale via
        ``backend.remove_peer`` BEFORE this hook runs, so only the
        plane-side bookkeeping (slots + refcounts) is released here."""
        pid = self._peer_ids.get(peer)
        if pid is None:
            return 0
        removed = 0
        for slot in list(self._peer_slots.get(pid, ())):
            key = self._ref_key(
                int(self._wid[slot]), self._cube[slot], pid
            )
            self._sub_refs.pop(key, None)  # index row already purged
            self._release_slot(slot, pid)
            removed += 1
        return removed

    def _grow(self, cap: int) -> None:
        """Double the capacity tier (pow2): reallocate every column,
        preserving slots. The next dispatch compiles the new tier —
        visible in device.retraces as a tier first hit, exactly like
        the query engine's capacity ladder."""
        def grow2(arr, fill, dtype, width=None):
            shape = (cap,) if width is None else (cap, width)
            out = np.full(shape, fill, dtype)
            out[: self._cap] = arr
            return out

        self._pos = grow2(self._pos, _DEAD_POS, np.float32, 3)
        self._vel = grow2(self._vel, 0.0, np.float32, 3)
        self._wid = grow2(self._wid, -1, np.int32)
        self._pid = grow2(self._pid, -1, np.int32)
        self._cube = grow2(self._cube, 0, np.int64, 3)
        self._live = grow2(self._live, False, bool)
        self._touched = grow2(self._touched, False, bool)
        self._cap = cap
        logger.info("entity plane grew to capacity tier %d", cap)

    # endregion

    # region: sim tick (ticker flush path)

    def dispatch_tick(self):
        """Launch one simulation tick from the host columns (event-loop
        thread; tick.sim.integrate span). Uploads the full capacity
        tier, launches the fused integrate+kNN kernel, and enqueues the
        D2H prefetch. Returns an opaque handle for ``collect_tick`` or
        None when idle / a previous tick is still in flight (pipelined
        flushes never stack sim ticks — the writeback of tick N is
        input to tick N+1)."""
        self._drain_pending()  # coalesced updates apply tick-edge
        if not self._slot_of or self._tick_inflight:
            return None
        t0 = time.perf_counter()
        cap = self._cap
        state = EntityState(
            position=jnp.asarray(self._pos),
            velocity=jnp.asarray(self._vel),
            world=jnp.asarray(self._wid),
            peer=jnp.asarray(self._pid),
        )
        new_state, targets, counts = self._tick_fn(state)
        for arr in (new_state.position, targets, counts):
            copy_async = getattr(arr, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        self._touched[:cap] = False
        self._tick_inflight = True
        self.dispatches += 1
        self.last_integrate_ms = (time.perf_counter() - t0) * 1e3
        if self.metrics is not None:
            self.metrics.observe_ms("sim.integrate_ms", self.last_integrate_ms)
        return {
            "pos": new_state.position,
            "targets": targets,
            "counts": counts,
            "cap": cap,
            "t0": t0,
        }

    def collect_tick(self, handle) -> dict:
        """Wait out the device and fetch results (worker thread;
        tick.sim.knn span). The three fetches below are the sim tick's
        designated device→host sync points; everything else stays
        vectorized. Also re-quantizes the integrated positions to
        cubes host-side in f64 — the AUTHORITATIVE quantizer, so the
        index coupling follows the golden grid, not the device's f32
        twin."""
        t0 = time.perf_counter()
        pos = np.asarray(handle["pos"])  # wql: allow(host-sync-in-sim-tick) — designated collect point
        targets = np.asarray(handle["targets"])  # wql: allow(host-sync-in-sim-tick) — designated collect point
        counts = np.asarray(handle["counts"])  # wql: allow(host-sync-in-sim-tick) — designated collect point
        cubes = cube_coords_batch(pos.astype(np.float64), self.cube_size)
        knn_ms = (time.perf_counter() - t0) * 1e3
        return {
            "pos": pos, "targets": targets, "counts": counts,
            "cubes": cubes, "cap": handle["cap"], "knn_ms": knn_ms,
        }

    def abort_tick(self) -> None:
        """Drop an in-flight tick without applying it (cancelled or
        errored flush): host columns stay authoritative and unchanged,
        the next dispatch simply re-integrates from them."""
        if self._tick_inflight:
            self._tick_inflight = False
            self.dropped_ticks += 1

    def apply(self, result: dict, trace=None,
              skip_frames: bool = False) -> list:
        """Integrate one collected tick back into the host authority
        (event-loop thread): position writeback, index churn through
        the base+delta path, neighbor-frame assembly. Returns
        ``(message, targets)`` delivery pairs for the tick's batched
        deliver. ``skip_frames`` (tick-deadline degradation) applies
        the writeback + churn but sheds the frame leg — counted, never
        silent."""
        self._tick_inflight = False
        t0 = time.perf_counter()
        cap = result["cap"]
        pos, cubes = result["pos"], result["cubes"]
        targets, counts = result["targets"], result["counts"]

        # 1. position writeback — every live slot that the wire did
        # NOT touch since dispatch (a client update must win over the
        # concurrent integration it never saw)
        wb = self._live[:cap] & ~self._touched[:cap]
        self._pos[:cap][wb] = pos[wb]

        # 2. index churn: slots whose authoritative cube moved. Only
        # written-back slots move here — touched slots re-quantize at
        # the NEXT applied tick from their client-given position.
        moved = wb & np.any(cubes != self._cube[:cap], axis=1)
        moved_slots = np.flatnonzero(moved)
        if moved_slots.size:
            self._apply_churn(moved_slots, cubes)
        self.last_churn = int(moved_slots.size)

        # 3. neighbor frames: one message per entity with >= 1 target,
        # fanned out to the owning peers of its k nearest co-cube
        # entities (the device already applied except-self per PEER)
        if skip_frames:
            pairs = []
            self.frames_skipped += 1
            if self.metrics is not None:
                self.metrics.inc("sim.frames_skipped")
        else:
            pairs = self._build_frames(pos, targets, counts, cap)

        self.applied_ticks += 1
        self.frames += len(pairs)
        self.last_apply_ms = (time.perf_counter() - t0) * 1e3
        self.last_knn_ms = result["knn_ms"]
        if self.metrics is not None:
            self.metrics.observe_ms("sim.knn_ms", result["knn_ms"])
            self.metrics.observe_ms("sim.apply_ms", self.last_apply_ms)
            if moved_slots.size:
                self.metrics.inc("sim.index_moves", int(moved_slots.size))
            if pairs:
                self.metrics.inc("sim.frames", len(pairs))
        if trace is not None:
            trace.tag(sim={
                "entities": len(self._slot_of),
                "frames": len(pairs),
                "index_moves": int(moved_slots.size),
                "integrate_ms": round(self.last_integrate_ms, 3),
                "knn_ms": round(result["knn_ms"], 3),
                "apply_ms": round(self.last_apply_ms, 3),
            })
        return pairs

    def _apply_churn(self, moved_slots: np.ndarray,
                     cubes: np.ndarray) -> None:
        """Move the index rows of slots whose cube changed, through the
        backend's delta path. Refcount transitions decide which moves
        actually touch the index (co-located entities of one peer share
        a row); the surviving adds/removes go down vectorized, grouped
        by world, via ``bulk_move_subscriptions`` when the backend has
        it (TPU/sharded) or per-row mutations otherwise."""
        old_cubes = self._cube[moved_slots].copy()
        new_cubes = cubes[moved_slots]
        wids = self._wid[moved_slots]
        pids = self._pid[moved_slots]
        self._cube[moved_slots] = new_cubes
        self.index_moves += int(moved_slots.size)

        # refcount transitions (O(churn) host work, like any index
        # mutation batch): rows crossing 0 materialize as index ops
        add_rows: list[int] = []
        rem_rows: list[int] = []
        refs = self._sub_refs
        for i in range(moved_slots.size):
            wid = int(wids[i])
            pid = int(pids[i])
            old_key = (wid, int(old_cubes[i, 0]), int(old_cubes[i, 1]),
                       int(old_cubes[i, 2]), pid)
            new_key = (wid, int(new_cubes[i, 0]), int(new_cubes[i, 1]),
                       int(new_cubes[i, 2]), pid)
            refs[old_key] -= 1
            if refs[old_key] <= 0:
                del refs[old_key]
                rem_rows.append(i)
            refs[new_key] += 1
            if refs[new_key] == 1:
                add_rows.append(i)

        bulk_move = getattr(self.backend, "bulk_move_subscriptions", None)
        for wid in np.unique(wids).tolist():
            world = self._world_names[wid]
            rem = [i for i in rem_rows if wids[i] == wid]
            add = [i for i in add_rows if wids[i] == wid]
            rem_peers = [self._peer_uuids[int(pids[i])] for i in rem]
            add_peers = [self._peer_uuids[int(pids[i])] for i in add]
            if bulk_move is not None:
                bulk_move(
                    world,
                    rem_peers, old_cubes[rem],
                    add_peers, new_cubes[add],
                )
            else:
                for peer, cube in zip(rem_peers, old_cubes[rem]):
                    self.backend.remove_subscription(
                        world, peer, tuple(int(c) for c in cube)
                    )
                for peer, cube in zip(add_peers, new_cubes[add]):
                    self.backend.add_subscription(
                        world, peer, tuple(int(c) for c in cube)
                    )
        # Make the churn visible to the device twin and run the LSM
        # compaction policy NOW: the query path calls flush() at every
        # dispatch, but an entity-sim-only server has no query
        # dispatches — without this the delta log (and its tombstones)
        # would grow without bound. No-op-cheap when nothing is dirty.
        self.backend.flush()

    def _build_frames(self, pos, targets, counts, cap: int) -> list:
        """Assemble per-entity neighbor frames: for every live entity
        with at least one resolved target, one LocalMessage carrying
        the entity's integrated position, addressed to the owning peers
        of its nearest neighbors. The message serializes ONCE in
        deliver_batch and fans out from there. O(entities with
        neighbors) host work — the delivery-path analog of the query
        engine's decode."""
        live = self._live[:cap]
        valid = targets >= 0
        has_any = live & valid.any(axis=1)
        rows = np.flatnonzero(has_any)
        if rows.size == 0:
            return []
        pairs = []
        peer_uuids = self._peer_uuids
        uuid_of = self._uuid_of
        world_names = self._world_names
        wid_col = self._wid
        pid_col = self._pid
        for row in rows.tolist():
            tgt_pids = np.unique(targets[row][valid[row]])
            targets_u = [peer_uuids[int(p)] for p in tgt_pids]
            position = Vector3(
                float(pos[row, 0]), float(pos[row, 1]), float(pos[row, 2])
            )
            world = world_names[int(wid_col[row])]
            msg = Message(
                instruction=Instruction.LOCAL_MESSAGE,
                parameter=PARAM_FRAME,
                sender_uuid=peer_uuids[int(pid_col[row])],
                world_name=world,
                position=position,
                entities=[Entity(
                    uuid=uuid_of[row], position=position,
                    world_name=world,
                )],
            )
            pairs.append((msg, targets_u))
        return pairs

    # endregion

    def stats(self) -> dict:
        return {
            "entities": len(self._slot_of),
            "capacity": self._cap,
            "peers": len(self._peer_slots),
            "worlds": len(self._world_names),
            "k": self.k,
            "registered": self.entities_registered,
            "removed": self.entities_removed,
            "updates": self.updates,
            "rejected": self.rejected,
            "dispatches": self.dispatches,
            "applied_ticks": self.applied_ticks,
            "dropped_ticks": self.dropped_ticks,
            "frames": self.frames,
            "frames_skipped": self.frames_skipped,
            "coalesced": self.coalesced,
            "pending": len(self._pending),
            "index_moves": self.index_moves,
            "index_rows": len(self._sub_refs),
            "last_integrate_ms": round(self.last_integrate_ms, 3),
            "last_knn_ms": round(self.last_knn_ms, 3),
            "last_apply_ms": round(self.last_apply_ms, 3),
            "last_churn": self.last_churn,
        }


def _decode_velocity(flex: bytes | None):
    """Wire velocity: ``Entity.flex`` carries 12 little-endian f32
    bytes (vx, vy, vz). Absent/short flex = no velocity change (zero
    for a fresh registration)."""
    if flex is None or len(flex) < 12:
        return None
    return np.frombuffer(flex[:12], dtype="<f4").astype(np.float32)
