"""ColumnarIngest: the wire→SoA entity fast path (PR 11).

Sits between the transport recv loop and the EntityPlane: a whole recv
batch's wire buffers go through ONE GIL-releasing native decode
(``protocol/entity_wire.wql_decode_entities``) that classifies each
buffer and lands every fast buffer's entities in shared SoA columns.
This module then walks the batch IN ARRIVAL ORDER, coalescing
consecutive fast buffers into one ``EntityPlane.ingest_columns`` run
(zero per-entity Python) and routing everything else — removals,
non-entity instructions, exotic encodings, malformed bytes — through
the transport's ordinary per-message path, so semantics never depend
on the fast path being available.

Admission parity with the router choke point: each fast message still
pays the governor's ``admit`` (entity class: token buckets + counting,
sheds only rate-limited abusers), the transport's unknown-sender drop
(``sender_known``), and the ``codec.decode``/``router.dispatch``
failpoints — fault injection and overload control see the columnar
path exactly as they see the object path.

A stale native library (``active`` False) degrades the whole batch to
the slow route: identical behavior, object-path speed.
"""

from __future__ import annotations

import logging
import uuid as uuid_mod

import numpy as np

from ..protocol import Instruction, entity_wire
from ..protocol.entity_wire import RECV_DRAIN_MAX  # noqa: F401 (re-export)
from ..robustness import failpoints

logger = logging.getLogger(__name__)

_MSG_COUNTER = {
    int(Instruction.GLOBAL_MESSAGE): "messages.global_message",
    int(Instruction.LOCAL_MESSAGE): "messages.local_message",
}


class ColumnarIngest:
    """One per server (``--entity-sim``). Event-loop owned."""

    def __init__(self, plane, sender_known, governor=None, metrics=None,
                 wire="auto", on_error=None):
        self.plane = plane
        self._sender_known = sender_known
        self._governor = governor
        self.metrics = metrics
        self._wire = entity_wire.shared() if wire == "auto" else wire
        self._on_error = on_error
        # stats (entity_ingest gauge)
        self.batches = 0        # recv batches through the native decode
        self.fast_messages = 0  # messages consumed columnar
        self.slow_messages = 0  # messages routed through the object path
        self.dropped = 0        # unknown sender / shed / decode-contained
        self.rows = 0           # entity rows staged columnar
        self.decode_fallbacks = 0  # native decode errors → object path

    @property
    def active(self) -> bool:
        """The native columnar decode is available (a stale ``.so``
        turns this off and every message takes the slow route)."""
        return (
            self._wire is not None
            and self._wire.can_decode
            and self.plane is not None
        )

    def stats(self) -> dict:
        return {
            "active": int(self.active),  # 0/1: prometheus-friendly
            "batches": self.batches,
            "fast_messages": self.fast_messages,
            "slow_messages": self.slow_messages,
            "dropped": self.dropped,
            "rows": self.rows,
            "decode_fallbacks": self.decode_fallbacks,
        }

    async def process_batch(self, datas: list[bytes], slow_route,
                            ctxs: list[tuple[int, int]] | None = None) -> None:
        """Consume one recv batch. ``slow_route(data, ctx)`` is the
        transport's ordinary single-message path (decode → router);
        per-message errors are contained here exactly like the
        transport's own loop contains them. Never raises.

        ``ctxs`` (clustered shards) carries the per-message router
        trace context the transport stripped off before the native
        classifier — slow-routed messages get theirs back so the
        object path still threads ``Message.trace_ctx``; columnar-
        consumed updates never materialize a Message (same as the
        single-process fast path) and close the e2e clock in the
        delivery plane instead."""
        if not self.active:
            for i, data in enumerate(datas):
                await self._slow(data, slow_route,
                                 ctxs[i] if ctxs else None)
            return
        self.batches += 1
        try:
            # entities.decode_native: the PR 11 fast path's loss
            # boundary — a native decode failure (or an armed chaos
            # fault) degrades THIS batch to the object route, counted,
            # with identical semantics
            failpoints.fire("entities.decode_native")
            res = self._wire.decode(datas)
        except Exception:
            self.decode_fallbacks += 1
            if self.metrics is not None:
                self.metrics.inc("sim.decode_fallbacks")
            logger.exception(
                "native entity decode failed — batch of %d messages "
                "degraded to the object path", len(datas),
            )
            for i, data in enumerate(datas):
                await self._slow(data, slow_route,
                                 ctxs[i] if ctxs else None)
            return
        run_idx: list[int] = []
        run_senders: list[uuid_mod.UUID] = []
        for i in range(len(datas)):
            if res.status[i]:
                try:
                    sender = self._admit(i, res)
                except Exception:
                    self._contain("columnar admission failed — "
                                  "message dropped")
                    continue
                if sender is not None:
                    run_idx.append(i)  # wql: allow(unbounded-ingest) — bounded by RECV_DRAIN_MAX, behind governor admit above
                    run_senders.append(sender)  # wql: allow(unbounded-ingest) — same bound
                    continue
                self.dropped += 1
                continue
            # a slow message breaks the run: flush staged work first so
            # per-entity arrival order survives (a removal after an
            # update must see the update already staged)
            self._flush_run(run_idx, run_senders, datas, res)
            await self._slow(datas[i], slow_route,
                             ctxs[i] if ctxs else None)
        self._flush_run(run_idx, run_senders, datas, res)

    async def _slow(self, data: bytes, slow_route,
                    ctx: tuple[int, int] | None = None) -> None:
        self.slow_messages += 1
        try:
            if ctx is not None:
                await slow_route(data, ctx)
            else:
                await slow_route(data)
        except Exception:
            self._contain("error processing inbound message — dropped")

    def _admit(self, i: int, res) -> uuid_mod.UUID | None:
        """Transport + governor admission for one fast message; None =
        drop (unknown sender, or shed by the governor — counted
        there). Mirrors the object path: codec.decode and
        router.dispatch failpoints fire here too."""
        failpoints.fire("codec.decode")
        sender = uuid_mod.UUID(bytes=res.sender_keys[i].tobytes())
        if not self._sender_known(sender):
            return None  # transport policy: unknown senders are ignored
        if self.metrics is not None:
            counter = _MSG_COUNTER.get(int(res.instr[i]))
            if counter is not None:
                self.metrics.inc(counter)
        failpoints.fire("router.dispatch")
        governor = self._governor
        if governor is not None and not governor.admit(
            Instruction(int(res.instr[i])), sender, True
        ):
            return None  # shed — classified and counted by the governor
        return sender

    def _flush_run(self, run_idx: list[int], run_senders: list,
                   datas: list[bytes], res) -> None:
        """Stage one run of consecutive fast messages as a single
        columnar pass through the plane."""
        if not run_idx:
            return
        try:
            worlds = []
            for i in run_idx:
                off = int(res.world_off[i])
                raw = datas[i][off:off + int(res.world_len[i])]
                worlds.append(raw.decode("utf-8"))
            counts = res.ent_count[run_idx]
            row_idx = np.concatenate([
                np.arange(
                    res.ent_start[i], res.ent_start[i] + res.ent_count[i]
                )
                for i in run_idx
            ])
            applied = self.plane.ingest_columns(
                run_senders, worlds, counts,
                res.uuid_keys[row_idx], res.pos[row_idx],
                res.vel[row_idx], res.has_vel[row_idx],
            )
            self.fast_messages += len(run_idx)
            self.rows += int(counts.sum())
            if self.metrics is not None:
                self.metrics.inc("messages.entity_batches", len(run_idx))
                if applied:
                    self.metrics.inc("messages.entity_ops", applied)
        except UnicodeDecodeError:
            # the object path would raise DeserializeError → dropped
            self._contain("invalid world bytes in entity batch — dropped")
        except Exception:
            self._contain("columnar staging failed — run dropped")
        finally:
            run_idx.clear()
            run_senders.clear()

    def _contain(self, msg: str) -> None:
        self.dropped += 1
        logger.exception(msg)
        if self._on_error is not None:
            try:
                self._on_error()
            except Exception:
                pass
