"""Long-lived task supervision: observe, restart with backoff, escalate.

Before this module, the server's long-lived loops (checkpoint timer,
staleness sweepers, ZMQ recv loop, durability applier, ticker pump)
were bare ``asyncio.create_task`` calls nobody awaited: one unhandled
exception and that subsystem was silently dead while the process kept
"running" — the worst failure mode a production server can have.

Every such loop now runs under a :class:`Supervisor` with a per-task
:class:`TaskPolicy`:

* a crash is logged with its traceback and counted
  (``supervisor.crashes``), then the task is **restarted** after an
  exponential backoff (``backoff_base`` doubling up to ``backoff_max``)
  while the **restart budget** lasts;
* a run that stays healthy for ``reset_after`` seconds refunds the
  budget and resets the backoff — a sweeper that crashes once a week
  must not drift toward permanent failure;
* when the budget is exhausted the task enters the ``failed`` state
  (the ``tasks_unhealthy`` gauge, wired into ``/healthz``); a
  **critical** task (ticker pump, ZMQ recv loop, durability applier)
  additionally **escalates** — the server's hook requests a clean
  shutdown, because a broker that can no longer receive or tick is
  better restarted by its orchestrator than left up and deaf.

``spawn_transient`` covers the short-lived per-tick stage tasks: no
restart (their batch is gone), but crashes are contained, logged and
counted instead of vanishing into a GC'd task object.

The ``tools/check`` rule ``unsupervised-task`` keeps this invariant
static: a raw ``create_task``/``ensure_future`` in ``engine/`` or
``transports/`` fails the lint unless deliberately pragma'd.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Awaitable, Callable

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class TaskPolicy:
    restart: bool = True        # restart after a crash (within budget)
    backoff_base: float = 0.5   # first restart delay, seconds
    backoff_max: float = 30.0   # backoff ceiling
    budget: int = 5             # restarts allowed per unhealthy streak
    reset_after: float = 60.0   # healthy-run seconds that refund budget
    critical: bool = False      # escalate when the budget is exhausted


class SupervisedTask:
    """One supervised long-lived task: the runner loop that owns the
    crash/restart/escalate state machine for a single factory."""

    def __init__(
        self,
        name: str,
        factory: Callable[[], Awaitable],
        policy: TaskPolicy,
        supervisor: "Supervisor",
    ):
        self.name = name
        self.factory = factory
        self.policy = policy
        self.state = "running"   # running | done | stopped | failed
        self.crashes = 0
        self.restarts = 0
        self._sup = supervisor
        self._runner = asyncio.create_task(self._run(), name=f"sup:{name}")

    @property
    def task(self) -> asyncio.Task:
        return self._runner

    def done(self) -> bool:
        return self._runner.done()

    def cancel(self) -> None:
        self._runner.cancel()

    async def stop(self) -> None:
        """Cancel the runner (and whatever factory run is in flight)
        and wait it out; idempotent."""
        if not self._runner.done():
            self._runner.cancel()
        try:
            await self._runner
        except (asyncio.CancelledError, Exception):
            pass

    async def _run(self) -> None:
        policy = self.policy
        backoff = policy.backoff_base
        while True:
            started = time.monotonic()
            try:
                await self.factory()
            except asyncio.CancelledError:
                self.state = "stopped"
                raise
            except Exception:
                self.crashes += 1
                self._sup._note_crash(self.name)
                logger.exception(
                    "supervised task %r crashed (crash #%d)",
                    self.name, self.crashes,
                )
                if time.monotonic() - started >= policy.reset_after:
                    # it ran healthily for a long stretch before this
                    # crash: refund the budget instead of letting rare
                    # independent crashes accumulate into a failure
                    self.restarts = 0
                    backoff = policy.backoff_base
                if not policy.restart or self.restarts >= policy.budget:
                    self.state = "failed"
                    self._sup._note_failure(self.name, self.policy.critical)
                    return
                self.restarts += 1
                self._sup._note_restart(self.name)
                logger.warning(
                    "restarting task %r in %.3gs (restart %d/%d)",
                    self.name, backoff, self.restarts, policy.budget,
                )
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, policy.backoff_max)
            else:
                # clean return is completion (restored-peer sweep), not
                # a crash — never restart it
                self.state = "done"
                return

    def snapshot(self) -> dict:
        return {
            "state": self.state,
            "crashes": self.crashes,
            "restarts": self.restarts,
            "critical": self.policy.critical,
        }


class Supervisor:
    """Registry of supervised tasks for one server instance."""

    def __init__(
        self,
        metrics=None,
        on_escalate: Callable[[str], None] | None = None,
        *,
        backoff_base: float = 0.5,
        budget: int = 5,
    ):
        self.metrics = metrics
        self.on_escalate = on_escalate
        self.backoff_base = backoff_base
        self.budget = budget
        self._tasks: dict[str, SupervisedTask] = {}
        self._transients: set[asyncio.Task] = set()
        self.transient_crashes = 0

    # region: spawning

    def policy(self, **overrides) -> TaskPolicy:
        """A TaskPolicy seeded with this supervisor's configured
        defaults (server config knobs)."""
        base = dict(backoff_base=self.backoff_base, budget=self.budget)
        base.update(overrides)
        return TaskPolicy(**base)

    def spawn(
        self,
        name: str,
        factory: Callable[[], Awaitable],
        *,
        critical: bool = False,
        policy: TaskPolicy | None = None,
    ) -> SupervisedTask:
        """Run ``factory`` under supervision. ``factory`` is re-invoked
        on each restart, so pass the coroutine *function*, not a
        coroutine object."""
        if policy is None:
            policy = self.policy(critical=critical)
        st = SupervisedTask(name, factory, policy, self)
        self._tasks[name] = st
        return st

    def spawn_transient(self, name: str, coro) -> asyncio.Task:
        """Crash-contained one-shot task (per-tick pipeline stages):
        no restart — its batch is gone — but the exception is logged
        and counted instead of dying inside a GC'd task object."""

        async def contained():
            try:
                return await coro
            except asyncio.CancelledError:
                raise
            except Exception:
                self.transient_crashes += 1
                self._note_crash(name)
                logger.exception("transient task %r crashed", name)
                return None

        task = asyncio.create_task(contained(), name=f"sup:{name}")
        self._transients.add(task)
        task.add_done_callback(self._transients.discard)
        return task

    # endregion

    # region: lifecycle + introspection

    async def stop(self) -> None:
        """Stop every supervised task and cancel outstanding
        transients. Final sweep of server shutdown — subsystems that
        need ordered teardown (ticker, durability applier, ZMQ recv)
        stop their own handles first; stopping an already-stopped
        handle is a no-op."""
        for st in list(self._tasks.values()):
            await st.stop()
        for task in list(self._transients):
            task.cancel()
        for task in list(self._transients):
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._transients.clear()

    def get(self, name: str) -> SupervisedTask | None:
        return self._tasks.get(name)

    def task_count(self) -> int:
        return len(self._tasks)

    def unhealthy_count(self) -> int:
        """Tasks that exhausted their restart budget — the
        ``tasks_unhealthy`` gauge surfaced by ``/healthz``."""
        return sum(1 for t in self._tasks.values() if t.state == "failed")

    def stats(self) -> dict:
        return {
            "tasks_unhealthy": self.unhealthy_count(),
            "crashes": sum(t.crashes for t in self._tasks.values())
            + self.transient_crashes,
            "restarts": sum(t.restarts for t in self._tasks.values()),
            "tasks": {
                name: t.snapshot() for name, t in self._tasks.items()
            },
        }

    # endregion

    # region: accounting hooks (called by SupervisedTask)

    def _note_crash(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc("supervisor.crashes")
            self.metrics.inc(f"supervisor.crashes.{name}")

    def _note_restart(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc("supervisor.restarts")
            self.metrics.inc(f"supervisor.restarts.{name}")

    def _note_failure(self, name: str, critical: bool) -> None:
        if self.metrics is not None:
            self.metrics.inc("supervisor.task_failures")
        if not critical:
            logger.error(
                "task %r exhausted its restart budget — marked "
                "unhealthy (see /healthz tasks_unhealthy)", name,
            )
            return
        logger.critical(
            "CRITICAL task %r exhausted its restart budget — "
            "escalating to clean server shutdown", name,
        )
        if self.metrics is not None:
            self.metrics.inc("supervisor.escalations")
        if self.on_escalate is not None:
            try:
                self.on_escalate(name)
            except Exception:
                logger.exception("escalation hook failed for %r", name)

    # endregion
