"""Degraded-mode spatial backend: contain, rebuild, fail over.

TPU-KNN-style fixed-shape device kernels are all-or-nothing: a failed
collect yields NO partial results (PAPERS.md TPU-KNN), and a device
backend whose internal mirror desyncs can poison every later tick. So
the accelerated backend gets a crash-containment wrapper with three
escalating responses:

1. **Contain** — a failed dispatch/collect resolves that batch through
   the CPU mirror instead, so fan-out continues (degraded) rather than
   dropping the tick.
2. **Rebuild** — after each contained failure (below the failover
   threshold) the inner backend is rebuilt from scratch out of the
   authoritative mirror via the normal bulk-load path — the same
   discipline as snapshot restore, so the rebuilt index is
   indistinguishable from one built by live traffic.
3. **Fail over** — ``failover_after`` CONSECUTIVE failures flip the
   wrapper to the CPU mirror permanently (process lifetime): metric
   (``resilience.failovers``), CRITICAL log, and a ``degraded`` flag
   on ``/healthz``. A 20 Hz tick served at CPU speed beats a dead
   server; the orchestrator decides when to restart onto healthy
   hardware.

The mirror is a :class:`CpuSpatialBackend` fed every mutation before
the inner backend sees it — authoritative by construction, and exactly
the engine queries fail over TO, so there is no translation step at
the worst possible moment. Mutation cost is a couple of dict ops per
subscription change, amortized noise next to the device work this
wrapper protects.

Thread note: ``collect_local_batch`` runs on the ticker's worker
thread. The mirror fallback there reads dicts the event loop may be
mutating; a torn iteration raises ``RuntimeError``, which the fallback
retries and then degrades to an empty fan-out for that batch — still
contained, never propagated.
"""

from __future__ import annotations

import logging
import uuid as uuid_mod
from typing import Callable, Sequence

from ..protocol.types import Vector3
from ..spatial.backend import Cube, LocalQuery, SpatialBackend
from ..spatial.cpu_backend import CpuSpatialBackend
from . import failpoints

logger = logging.getLogger(__name__)


def _fallback_queries(fallback) -> list[LocalQuery] | None:
    """Normalize a re-resolve source to LocalQuery objects: the list
    path stores the queries themselves, the staged path the ticker's
    retained ``(message, query)`` pairs. None when there is nothing to
    re-resolve from."""
    if fallback is None:
        return None
    return [
        pair[1] if isinstance(pair, tuple) else pair for pair in fallback
    ]


class _Resolved:
    """Dispatch handle for a batch already resolved by the mirror."""

    __slots__ = ("targets",)

    def __init__(self, targets):
        self.targets = targets


class _Inflight:
    """Dispatch handle wrapping the inner backend's own handle plus
    the queries needed to re-resolve through the mirror on failure."""

    __slots__ = ("handle", "queries")

    def __init__(self, handle, queries):
        self.handle = handle
        self.queries = queries


class ResilientBackend(SpatialBackend):
    def __init__(
        self,
        inner: SpatialBackend,
        *,
        factory: Callable[[], SpatialBackend] | None = None,
        failover_after: int = 3,
        metrics=None,
    ):
        super().__init__(inner.cube_size)
        self.inner = inner
        self._factory = factory
        self.mirror = CpuSpatialBackend(inner.cube_size)
        self.failover_after = max(1, int(failover_after))
        self.metrics = metrics
        self.failures = 0        # consecutive (reset by a healthy collect)
        self.total_failures = 0
        self.rebuilds = 0
        self.degraded_batches = 0
        self.failed_over = False
        #: invoked BEFORE every rebuild/failover restore: dependents
        #: holding device state derived from the inner backend (the
        #: entity plane's twin + dirty bitmap) must invalidate it —
        #: a rebuild mid-sim-tick would otherwise scatter dirty rows
        #: onto a twin the restore just made stale. The server wires
        #: EntityPlane.abort_tick here.
        self.on_rebuild: Callable[[], None] | None = None

    # region: failure machinery

    def _note_failure(self, stage: str) -> None:
        """Record one inner-backend failure (called from an except
        block). Escalates: rebuild below the threshold, fail over at
        it."""
        self.failures += 1
        self.total_failures += 1
        if self.metrics is not None:
            self.metrics.inc("resilience.failures")
            self.metrics.inc(f"resilience.failures.{stage}")
        logger.exception(
            "spatial backend %s failed (consecutive failure %d/%d) — "
            "resolved through the CPU mirror",
            stage, self.failures, self.failover_after,
        )
        if self.failed_over:
            return
        if self.failures >= self.failover_after:
            self._failover(stage)
        else:
            self._rebuild()

    def _notify_rebuild(self) -> None:
        """Tell dependents the inner backend (and anything derived
        from it) is about to be replaced. Must never block the
        containment path — a raising hook is logged and dropped.
        May fire from the collect worker thread (collect failures):
        the wired hook (abort_tick) is idempotent flag-flipping."""
        if self.on_rebuild is None:
            return
        try:
            self.on_rebuild()
        except Exception:
            logger.exception("on_rebuild hook failed — continuing")

    def _failover(self, stage: str) -> None:
        self._notify_rebuild()
        self.failed_over = True
        if self.metrics is not None:
            self.metrics.inc("resilience.failovers")
        logger.critical(
            "spatial backend failed %d consecutive times (last: %s) — "
            "FAILING OVER to the CPU mirror; the device backend is "
            "abandoned for the rest of this process (see /healthz)",
            self.failures, stage,
        )

    def _rebuild(self) -> None:
        """Reconstruct the inner backend from the authoritative mirror
        through the normal bulk-load path (same as snapshot restore).
        Without a factory the broken instance is kept and the next
        failure escalates toward failover."""
        if self._factory is None:
            return
        # invalidate dependent device state BEFORE the restore: an
        # in-flight sim tick's writeback/scatter must not land on a
        # twin whose backing index this rebuild is replacing
        self._notify_rebuild()
        try:
            fresh = self._factory()
            worlds, peers, wid, cube, pid = self.mirror.export_rows()
            for wid_i, world in enumerate(worlds):
                sel = wid == wid_i
                if sel.any():
                    fresh.bulk_add_subscriptions(
                        world, [peers[i] for i in pid[sel]], cube[sel]
                    )
            fresh.flush()
            self.inner = fresh
            self.rebuilds += 1
            if self.metrics is not None:
                self.metrics.inc("resilience.rebuilds")
            logger.warning(
                "spatial backend rebuilt from the authoritative mirror "
                "(%d rows, rebuild #%d)", len(pid), self.rebuilds,
            )
        except Exception:
            logger.exception(
                "spatial backend rebuild failed — keeping the broken "
                "instance; further failures will fail over to CPU"
            )

    def _mirror_match(
        self, queries: Sequence[LocalQuery]
    ) -> list[list[uuid_mod.UUID]]:
        """Mirror-resolve a batch, tolerating the worker-thread/-loop
        race documented in the module docstring."""
        for _ in range(3):
            try:
                return self.mirror.match_local_batch(queries)
            except RuntimeError:
                continue  # torn dict/set iteration under mutation
        return [[] for _ in queries]

    def status(self) -> dict:
        """State for /healthz and the ``resilience`` gauge."""
        return {
            "degraded": self.failed_over,
            "failed_over": self.failed_over,
            "consecutive_failures": self.failures,
            "failures": self.total_failures,
            "rebuilds": self.rebuilds,
            "degraded_batches": self.degraded_batches,
            "inner": type(self.inner).__name__,
        }

    # endregion

    # region: mutations (mirror first — it is the authority)

    def add_subscription(
        self, world: str, peer: uuid_mod.UUID, pos: Vector3 | Cube
    ) -> bool:
        out = self.mirror.add_subscription(world, peer, pos)
        if not self.failed_over:
            try:
                self.inner.add_subscription(world, peer, pos)
            except Exception:
                self._note_failure("mutate")
        return out

    def remove_subscription(
        self, world: str, peer: uuid_mod.UUID, pos: Vector3 | Cube
    ) -> bool:
        out = self.mirror.remove_subscription(world, peer, pos)
        if not self.failed_over:
            try:
                self.inner.remove_subscription(world, peer, pos)
            except Exception:
                self._note_failure("mutate")
        return out

    def remove_peer(self, peer: uuid_mod.UUID) -> bool:
        out = self.mirror.remove_peer(peer)
        if not self.failed_over:
            try:
                self.inner.remove_peer(peer)
            except Exception:
                self._note_failure("mutate")
        return out

    def bulk_add_subscriptions(self, world, peers, cubes) -> int:
        out = self.mirror.bulk_add_subscriptions(world, peers, cubes)
        if not self.failed_over:
            try:
                self.inner.bulk_add_subscriptions(world, peers, cubes)
            except Exception:
                self._note_failure("mutate")
        return out

    def bulk_remove_subscriptions(self, world, peers, cubes) -> int:
        """Explicit override: without it the call would fall through
        ``__getattr__`` straight to the inner backend, silently
        bypassing the mirror — a later rebuild would resurrect the
        removed rows. The CPU mirror has no bulk remove; per-row
        removal is its reference path anyway."""
        out = 0
        for peer, cube in zip(peers, cubes):
            if self.mirror.remove_subscription(
                world, peer, tuple(int(c) for c in cube)
            ):
                out += 1
        if not self.failed_over:
            try:
                self.inner.bulk_remove_subscriptions(world, peers, cubes)
            except Exception:
                self._note_failure("mutate")
        return out

    def bulk_move_subscriptions(
        self, world, rem_peers, rem_cubes, add_peers, add_cubes,
    ) -> tuple[int, int]:
        """Moving-object churn (entities/plane.py) with the mirror
        kept authoritative on both sides of the move."""
        removed = self.bulk_remove_subscriptions(world, rem_peers, rem_cubes)
        added = self.bulk_add_subscriptions(world, add_peers, add_cubes)
        return removed, added

    def flush(self) -> None:
        if not self.failed_over:
            try:
                self.inner.flush()
            except Exception:
                self._note_failure("flush")

    # endregion

    # region: queries

    def query_cube(self, world: str, pos) -> set[uuid_mod.UUID]:
        if not self.failed_over:
            try:
                return self.inner.query_cube(world, pos)
            except Exception:
                self._note_failure("query")
        return self.mirror.query_cube(world, pos)

    def query_world(self, world: str) -> set[uuid_mod.UUID]:
        if not self.failed_over:
            try:
                return self.inner.query_world(world)
            except Exception:
                self._note_failure("query")
        return self.mirror.query_world(world)

    def match_local_batch(
        self, queries: Sequence[LocalQuery]
    ) -> list[list[uuid_mod.UUID]]:
        if not self.failed_over:
            try:
                return self.inner.match_local_batch(queries)
            except Exception:
                self._note_failure("match")
                self.degraded_batches += 1
        return self._mirror_match(queries)

    # endregion

    # region: two-phase tick batch

    def dispatch_local_batch(self, queries: Sequence[LocalQuery]):
        if not self.failed_over:
            try:
                failpoints.fire("backend.dispatch")
                return _Inflight(
                    self.inner.dispatch_local_batch(queries), list(queries)
                )
            except Exception:
                self._note_failure("dispatch")
                self.degraded_batches += 1
        return _Resolved(self._mirror_match(queries))

    # region: staged columnar dispatch (engine/staging.py)

    def supports_staged_dispatch(self) -> bool:
        # even failed-over: the staged call degrades through the
        # fallback pairs below, so the ticker need not re-probe
        return self.inner.supports_staged_dispatch()

    def interning_maps(self):
        return self.inner.interning_maps()

    def staging_epoch(self) -> int:
        """Rebuilds replace ``inner`` (and its interning dicts)
        wholesale — ids staged before the swap are meaningless after
        it. Folding the rebuild/failover counters into the epoch makes
        the ticker fall back to the object-list path for exactly the
        windows that straddle a swap."""
        return (
            self.inner.staging_epoch()
            + 2 * self.rebuilds
            + int(self.failed_over)
        )

    def dispatch_staged_batch(
        self, world_ids, positions, sender_ids, repls,
        kinds=None, params=None, fallback=None,
    ):
        """Staged dispatch with the same containment as the list path.
        The mirror fallback needs LocalQuery objects — the staged
        columns carry interned ids that die with a failed inner
        backend — so the ticker's retained ``(message, query)`` pairs
        (``fallback``) are the re-resolve source; extracting them is
        O(m) Python paid ONLY on the failure path. The query-library
        ``kinds``/``params`` lanes pass straight through: on the
        degraded path the fallback LocalQuery rows still carry their
        kind, so the mirror answers them through the CPU oracles
        (``SpatialBackend.match_local_batch``) with identical
        semantics."""
        if not self.failed_over:
            try:
                failpoints.fire("backend.dispatch")
                return _Inflight(
                    self.inner.dispatch_staged_batch(
                        world_ids, positions, sender_ids, repls,
                        kinds, params,
                    ),
                    fallback,
                )
            except Exception:
                self._note_failure("dispatch")
                self.degraded_batches += 1
        queries = _fallback_queries(fallback)
        if queries is None:
            # no fallback pairs: still contained — an empty fan-out
            # per query beats a propagated dispatch error
            return _Resolved([[] for _ in range(len(world_ids))])
        return _Resolved(self._mirror_match(queries))

    # endregion

    def collect_local_batch(self, handle) -> list[list[uuid_mod.UUID]]:
        if isinstance(handle, _Resolved):
            return handle.targets
        try:
            failpoints.fire("backend.collect")
            out = self.inner.collect_local_batch(handle.handle)
        except Exception:
            self._note_failure("collect")
            self.degraded_batches += 1
            return self._mirror_match(_fallback_queries(handle.queries) or [])
        self.failures = 0  # a full dispatch→collect proves health
        return out

    # endregion

    # region: introspection (the mirror is the authority)

    def export_rows(self):
        return self.mirror.export_rows()

    def subscription_count(self) -> int:
        return self.mirror.subscription_count()

    def world_names(self) -> list[str]:
        return self.mirror.world_names()

    def cube_count(self, world: str) -> int:
        return self.mirror.cube_count(world)

    def __getattr__(self, name: str):
        # anything else (device_stats, wait_compaction, match_arrays…)
        # passes through to the inner backend
        inner = self.__dict__.get("inner")
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)

    # endregion
