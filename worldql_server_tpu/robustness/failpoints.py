"""Named fault-injection failpoints.

Every crash-containment claim in this package is only as good as the
failures used to prove it, so this module gives each boundary we care
about a NAMED injection site (the catalog lives in the README): store
init/insert/delete, WAL append/fsync, the write-behind applier batch,
backend dispatch/collect, transport send, codec decode, the router
dispatch, and the long-lived loop bodies (ticker pump, ZMQ recv).

Design constraints, in order:

* **Near-zero overhead when off.** ``fire()``/``afire()`` are module
  functions whose first (and usually only) action is a truthiness
  check on the registry's point dict — one dict bool per call site,
  no string formatting, no lock. Production runs with no
  ``WQL_FAILPOINTS`` pay essentially nothing.
* **Deterministic under a seed.** Probabilistic points draw from one
  ``random.Random`` owned by the registry, so a seeded chaos run
  fires the same faults in the same order every time (modulo event
  scheduling, which the chaos suite's assertions are written to
  tolerate).
* **Accounted.** Each point counts ``hits`` (site reached while the
  point was armed) and ``fired`` (fault actually injected); the server
  exports ``fired`` per point as the ``failpoints`` metrics gauge, and
  the chaos suite asserts the registry and ``/metrics`` agree — no
  fault may ever be injected invisibly.

Spec syntax (env ``WQL_FAILPOINTS``, CLI ``--failpoints``, or the
optional HTTP admin endpoint)::

    name=error[:P][:xN] | name=delay:DUR[:P][:xN] | name=state:VALUE[:P][:xN]

comma-separated; ``P`` is a fire probability in (0, 1] (default 1),
``xN`` caps total fires at N, ``DUR`` is ``50ms``/``0.5s``/bare
milliseconds. ``state`` is a VALUE-injection action: it never raises
or sleeps — a subsystem that polls :func:`forced` reads the armed
value (fires counted like any other point). The overload governor's
``overload.force_state`` point uses it so chaos can drive every
state-machine transition deterministically. Example::

    WQL_FAILPOINTS=store.insert=error:0.2,wal.fsync=delay:5ms,backend.collect=error:1:x3

The registry is process-global on purpose: injection sites are plain
module-level calls with no object to thread a handle through, exactly
like the logging module. Tests reset it around themselves
(``reset()``).
"""

from __future__ import annotations

import asyncio
import logging
import random
import re
import time

logger = logging.getLogger(__name__)


class FailpointError(RuntimeError):
    """The injected fault: raised by an armed ``error`` failpoint."""

    def __init__(self, name: str):
        super().__init__(f"failpoint {name!r} fired")
        self.failpoint = name


class FailpointSpecError(ValueError):
    """A failpoint spec string failed to parse."""


_DUR_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|us)?$")


def _parse_duration_s(raw: str) -> float:
    m = _DUR_RE.match(raw)
    if not m:
        raise FailpointSpecError(f"bad delay duration {raw!r}")
    value = float(m.group(1))
    unit = m.group(2) or "ms"
    return value * {"us": 1e-6, "ms": 1e-3, "s": 1.0}[unit]


class _Point:
    __slots__ = ("name", "spec", "action", "delay_s", "prob", "max_fires",
                 "hits", "fired", "value")

    def __init__(self, name: str, spec: str):
        self.name = name
        self.spec = spec
        self.hits = 0
        self.fired = 0
        parts = spec.split(":")
        self.action = parts[0]
        self.delay_s = 0.0
        self.prob = 1.0
        self.max_fires: int | None = None
        self.value: str | None = None
        if self.action == "error":
            rest = parts[1:]
        elif self.action == "delay":
            if len(parts) < 2:
                raise FailpointSpecError(
                    f"{name}: delay needs a duration (delay:50ms)"
                )
            self.delay_s = _parse_duration_s(parts[1])
            rest = parts[2:]
        elif self.action == "state":
            if len(parts) < 2 or not parts[1]:
                raise FailpointSpecError(
                    f"{name}: state needs a value (state:shed_high)"
                )
            self.value = parts[1]
            rest = parts[2:]
        else:
            raise FailpointSpecError(
                f"{name}: unknown action {self.action!r} "
                "(expected error|delay|state)"
            )
        for tok in rest:
            if tok.startswith("x"):
                try:
                    self.max_fires = int(tok[1:])
                except ValueError:
                    raise FailpointSpecError(
                        f"{name}: bad fire cap {tok!r}"
                    ) from None
            else:
                try:
                    self.prob = float(tok)
                except ValueError:
                    raise FailpointSpecError(
                        f"{name}: bad probability {tok!r}"
                    ) from None
                if not 0.0 < self.prob <= 1.0:
                    raise FailpointSpecError(
                        f"{name}: probability must be in (0, 1]"
                    )


def parse_spec(spec: str) -> dict[str, _Point]:
    """Spec string → {name: point}; raises :class:`FailpointSpecError`
    on any malformed entry (config validation uses this without
    arming anything)."""
    points: dict[str, _Point] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, action = entry.partition("=")
        if not sep or not name.strip():
            raise FailpointSpecError(f"bad failpoint entry {entry!r}")
        name = name.strip()
        points[name] = _Point(name, action.strip())
    return points


class FailpointRegistry:
    """All armed failpoints plus their fire accounting."""

    def __init__(self, seed: int | None = None):
        self._points: dict[str, _Point] = {}
        self._rng = random.Random(seed)
        #: cumulative fired counts, kept across configure()/clear() so a
        #: chaos run can re-arm points without losing the audit trail
        self._fired_total: dict[str, int] = {}

    # region: configuration

    def configure(self, spec: str, *, seed: int | None = None) -> None:
        """Replace the armed set from a spec string (see module doc).
        An empty spec disarms everything."""
        points = parse_spec(spec)
        if seed is not None:
            self._rng = random.Random(seed)
        self._points = points
        if points:
            logger.warning(
                "failpoints armed: %s",
                ",".join(f"{p.name}={p.spec}" for p in points.values()),
            )

    def set(self, name: str, action: str) -> None:
        """Arm (or re-arm) one failpoint without touching the others."""
        # rebuild the dict so fire()'s lock-free read never sees a
        # half-updated mapping
        points = dict(self._points)
        points[name] = _Point(name, action)
        self._points = points

    def clear(self, name: str | None = None) -> None:
        """Disarm one failpoint, or all of them."""
        if name is None:
            self._points = {}
        else:
            points = dict(self._points)
            points.pop(name, None)
            self._points = points

    def seed(self, seed: int | None) -> None:
        self._rng = random.Random(seed)

    def reset(self) -> None:
        """Disarm everything AND zero the accounting (tests)."""
        self._points = {}
        self._fired_total = {}

    def active(self) -> bool:
        return bool(self._points)

    # endregion

    # region: firing

    def _should_fire(self, point: _Point) -> bool:
        point.hits += 1
        if point.max_fires is not None and point.fired >= point.max_fires:
            return False
        if point.prob < 1.0 and self._rng.random() >= point.prob:
            return False
        point.fired += 1
        self._fired_total[point.name] = (
            self._fired_total.get(point.name, 0) + 1
        )
        return True

    def fire(self, name: str) -> None:
        """Synchronous injection site. ``delay`` blocks the calling
        thread (worker-thread sites: WAL fsync); ``error`` raises
        :class:`FailpointError`; ``state`` is inert here (it only
        feeds :meth:`forced_value` polls)."""
        point = self._points.get(name)
        if point is None or not self._should_fire(point):
            return
        if point.action == "delay":
            time.sleep(point.delay_s)
            return
        if point.action == "state":
            return
        raise FailpointError(name)

    async def afire(self, name: str) -> None:
        """Async injection site: ``delay`` yields to the loop instead
        of blocking it."""
        point = self._points.get(name)
        if point is None or not self._should_fire(point):
            return
        if point.action == "delay":
            await asyncio.sleep(point.delay_s)
            return
        if point.action == "state":
            return
        raise FailpointError(name)

    def forced_value(self, name: str) -> str | None:
        """Value-injection poll: the armed ``state:<value>`` payload,
        or None (not armed / not a state point / prob-xN said no).
        Every returned value counts as a fire, so forced transitions
        stay visible in the failpoints audit gauge."""
        point = self._points.get(name)
        if point is None or point.action != "state":
            return None
        if not self._should_fire(point):
            return None
        return point.value

    # endregion

    # region: accounting

    def fired(self, name: str) -> int:
        return self._fired_total.get(name, 0)

    def note_remote_fires(self, deltas: dict) -> None:
        """Fold fire counts observed in ANOTHER process into the audit
        total. Delivery workers arm their own per-process registry
        (the spec rides the spawn args) and report cumulative fires
        over the control channel; the plane diffs consecutive packets
        and folds the deltas here, so the ``failpoints`` gauge audits
        the whole plane — a fault injected in a sender worker is never
        invisible to the parent's accounting."""
        for name, n in deltas.items():
            if isinstance(n, int) and n > 0:
                self._fired_total[name] = (
                    self._fired_total.get(name, 0) + n
                )

    def fired_counts(self) -> dict[str, int]:
        """{failpoint: total fires} — the ``failpoints`` metrics gauge.
        Includes disarmed points that fired earlier, so a chaos run's
        audit survives the verification phase disarming everything."""
        return dict(self._fired_total)

    def stats(self) -> dict:
        """Full per-point state for the admin endpoint."""
        out = {}
        for name, point in self._points.items():
            out[name] = {
                "spec": point.spec,
                "hits": point.hits,
                "fired": point.fired,
            }
        for name, fired in self._fired_total.items():
            if name not in out:
                out[name] = {"spec": None, "hits": None, "fired": fired}
        return out

    # endregion


#: process-global registry — injection sites are bare module calls
registry = FailpointRegistry()


def fire(name: str) -> None:
    """Hot-path sync injection site; no-ops in one dict-bool when no
    failpoint is armed."""
    if registry._points:
        registry.fire(name)


async def afire(name: str) -> None:
    """Hot-path async injection site (loop-side boundaries)."""
    if registry._points:
        await registry.afire(name)


def forced(name: str) -> str | None:
    """Hot-path value-injection poll; one dict-bool when nothing is
    armed (the overload governor calls this every evaluation)."""
    if registry._points:
        return registry.forced_value(name)
    return None
