"""Overload control plane: admission, priority shedding, degradation.

PR 4 made the server survive *faults*; this module makes it survive
*load*. Manycore range-query serving (arXiv:1411.3212) and TPU-KNN
(arXiv:2206.14286) both assume the batch fed to the device is bounded
and well-formed — the :class:`OverloadGovernor` is what guarantees
that invariant under hostile offered load, so the device pipeline
stays saturated instead of the event loop drowning.

One governor per server, driven by live signals the repo already
measures:

* **tick wall vs budget** — ``TickBatcher`` reports every flush wall
  (``note_tick``); K consecutive ticks over ``tick_budget_ms`` is the
  deadline-degradation trigger AND a state-machine signal;
* **queue depth** — the ticker's pending batch as a fraction of
  ``max_batch`` (``note_queue_depth`` fires from the enqueue path, so
  a storm escalates mid-window, not one tick late);
* **event-loop lag** — ``loop.lag_ms`` from the PR 5 ``LoopMonitor``
  when tracing is on;
* **RSS** — ``/proc/self/statm`` against ``rss_limit_mb`` (0 = off).

The state machine is hysteretic — ``OK → SHED_LOW → SHED_HIGH →
REJECT``. Escalation is immediate (one sample over an enter
threshold); de-escalation steps DOWN one state only after
``recover_ticks`` consecutive samples below the exit thresholds,
which sit at ``hysteresis`` (default 0.8×) of the enter thresholds —
a signal parked exactly on a boundary cannot flap the state.

Priority classes at admission (``admit``), most-durable first:

=========  ====================================================
record     RecordCreate/Update/Delete/Read — durable, acked:
           NEVER shed, in any state (the token bucket counts
           them but never drops them).
entity     entity-update batches — never rejected; under
           ``SHED_LOW``+ the EntityPlane coalesces them
           last-write-wins per uuid (lossless for position
           streams — the newest position subsumes the ones it
           overwrote).
global     GlobalMessages — shed LAST: dropped only in REJECT.
local      LocalMessage fan-out queries — shed drop-OLDEST: the
           ticker queue is capped at ``2 × max_batch`` and evicts
           the stalest queued query when full; REJECT refuses
           them at ingest.
control    heartbeats — always admitted (liveness must survive
           overload; an evicted-for-silence peer helps nobody).
=========  ====================================================

Per-peer token buckets (``peer_rate`` msgs/s, ``peer_burst`` burst)
stop one chatty client from starving the rest: a limited message is
dropped (``peers.rate_limited``) unless it is a record op, and
``evict_after`` consecutive limited messages trigger the eviction
hook (``peers.evicted_rate_limited`` — configurable; 0 never evicts).

Tick-deadline degradation: ``deadline_k`` consecutive budget busts
halve the admitted batch tier (floor ``min_batch``) and skip the
entity neighbor-frame fan-out every other tick; ``recover_ticks``
consecutive in-budget ticks double the tier back (full service once
it reaches ``max_batch`` again).

Everything is observable, not silent: the ``overload`` gauge carries
state + counters into ``/metrics`` and ``/healthz``, the ticker tags
the governor state onto every tick trace, and the
``overload.force_state`` failpoint (``state:<name>`` action) lets
chaos drive every transition deterministically.

``--overload off`` (the default) never constructs this class — the
server's ingest paths keep today's behavior byte for byte.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import time
import uuid as uuid_mod

from ..protocol.types import Instruction
from . import failpoints

logger = logging.getLogger(__name__)

#: governor states, mildest first — list order IS escalation order
OK = "ok"
SHED_LOW = "shed_low"
SHED_HIGH = "shed_high"
REJECT = "reject"
STATES = (OK, SHED_LOW, SHED_HIGH, REJECT)
_LEVEL = {s: i for i, s in enumerate(STATES)}

#: admission classes (priority order documented in the module doc)
CLASS_RECORD = "record"
CLASS_ENTITY = "entity"
CLASS_GLOBAL = "global"
CLASS_LOCAL = "local"
CLASS_SUBSCRIBE = "subscribe"
CLASS_CONTROL = "control"
#: handshakes are an admission class too (ISSUE 12): a reconnect storm
#: must not be able to starve the tick with connect-back work. New
#: connects shed FIRST (SHED_HIGH+); resumes — peers with parked state
#: the server is already holding — shed LAST (REJECT only, and even
#: there a token bucket keeps admitting a bounded trickle so a mass
#: reconnect drains instead of livelocking).
CLASS_HS_NEW = "handshake_new"
CLASS_HS_RESUME = "handshake_resume"

_CLASS_OF = {
    Instruction.LOCAL_MESSAGE: CLASS_LOCAL,
    Instruction.GLOBAL_MESSAGE: CLASS_GLOBAL,
    Instruction.RECORD_CREATE: CLASS_RECORD,
    Instruction.RECORD_READ: CLASS_RECORD,
    Instruction.RECORD_UPDATE: CLASS_RECORD,
    Instruction.RECORD_DELETE: CLASS_RECORD,
    Instruction.AREA_SUBSCRIBE: CLASS_SUBSCRIBE,
    Instruction.AREA_UNSUBSCRIBE: CLASS_SUBSCRIBE,
}

#: enter thresholds per escalated level (SHED_LOW, SHED_HIGH, REJECT);
#: exit thresholds are ``hysteresis`` × these
_TICK_RATIO = (1.0, 2.0, 4.0)     # tick wall / tick budget
_QUEUE_FRAC = (0.5, 1.0, 2.0)     # queue depth / max_batch
_LAG_MS = (50.0, 250.0, 1000.0)   # event-loop scheduling lag
_RSS_FRAC = (0.85, 0.95, 1.05)    # RSS / rss_limit

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

#: failpoint driving deterministic transitions (chaos):
#:   WQL_FAILPOINTS=overload.force_state=state:shed_high
FORCE_STATE_FAILPOINT = "overload.force_state"


def read_rss_bytes() -> int:
    """Current resident set from /proc (Linux); 0 when unreadable —
    an absent signal must disable itself, not crash the governor."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except Exception:
        return 0


class OverloadGovernor:
    """Hysteretic overload state machine + priority-classed admission
    for one server. Event-loop owned (like the router it gates)."""

    def __init__(
        self,
        *,
        max_batch: int = 16_384,
        tick_budget_ms: float = 0.0,
        deadline_k: int = 3,
        recover_ticks: int = 5,
        min_batch: int = 256,
        peer_rate: float = 0.0,
        peer_burst: int = 0,
        evict_after: int = 0,
        rss_limit_mb: int = 0,
        hysteresis: float = 0.8,
        sample_interval: float = 0.25,
        resume_rate: float = 200.0,
        resume_burst: int = 0,
        metrics=None,
        loop_monitor=None,
        on_evict=None,
        clock=time.monotonic,
    ):
        self.max_batch = int(max_batch)
        self.tick_budget_ms = float(tick_budget_ms)
        self.deadline_k = max(1, int(deadline_k))
        self.recover_ticks = max(1, int(recover_ticks))
        self.min_batch = max(1, min(int(min_batch), self.max_batch))
        self.hysteresis = float(hysteresis)
        self.sample_interval = float(sample_interval)
        self.metrics = metrics
        self.loop_monitor = loop_monitor
        self.on_evict = on_evict
        self._clock = clock

        # per-peer token buckets: uuid → [tokens, t_refill, limited_streak]
        self.peer_rate = float(peer_rate)
        self.peer_burst = int(peer_burst) if peer_burst else max(
            1, int(2 * peer_rate)
        )
        self.evict_after = int(evict_after)
        self._buckets: dict[uuid_mod.UUID, list] = {}
        self._evicting: set[uuid_mod.UUID] = set()

        # handshake admission (session continuity, ISSUE 12): the
        # resume bucket bounds how many parked-state rebinds REJECT
        # still admits; the hint bucket bounds refusal replies so the
        # retry-after path can't itself be driven as a reflector.
        self.resume_rate = float(resume_rate)
        self.resume_burst = int(resume_burst) if resume_burst else max(
            1, int(2 * self.resume_rate)
        )
        self._resume_bucket = [float(self.resume_burst), self._clock()]
        self._hint_bucket = [50.0, self._clock()]
        #: jittered retry-after hints: a storm told to retry at the
        #: same instant just re-synchronizes itself — the jitter source
        #: is deliberately unseeded (de-correlating peers is the point)
        self._jitter = random.Random()

        self._state = OK
        self._recover = 0          # consecutive below-state samples
        self._busts = 0            # consecutive over-budget ticks
        self._healthy_ticks = 0    # consecutive in-budget ticks (tier)
        self._admitted = self.max_batch
        self._frame_parity = False
        self._last_tick_ms = 0.0
        self._queue_depth = 0
        self._depth_bucket = 0
        self._rss_bytes = 0
        self._rss_read_at = 0.0
        self._rss_limit_bytes = int(rss_limit_mb) * (1 << 20)

        # counters (also pushed into the metrics registry so the audit
        # invariant "shed work is fully accounted" holds in /metrics)
        self.ticks = 0
        self.transitions = 0
        self.peak_level = 0
        self.shed = {
            CLASS_LOCAL: 0, CLASS_GLOBAL: 0,
            CLASS_HS_NEW: 0, CLASS_HS_RESUME: 0,
        }
        self.handshakes_admitted = 0
        self.drop_oldest = 0
        self.rate_limited = 0
        self.tier_degradations = 0

    # region: state machine

    @property
    def state(self) -> str:
        return self._state

    @property
    def level(self) -> int:
        return _LEVEL[self._state]

    @property
    def admitted_batch(self) -> int:
        """Current admitted batch tier: ``max_batch`` at full service,
        halved per deadline-degradation step down to ``min_batch``."""
        return self._admitted

    def degraded(self) -> bool:
        return self._admitted < self.max_batch

    def note_tick(self, tick_ms: float, queue_depth: int) -> None:
        """One completed ticker flush: feed the deadline-degradation
        counters and re-evaluate the state machine. The ticker calls
        this from ``_account`` (real ticks) and ``note_idle`` (empty
        windows), so recovery keeps sampling after load drops."""
        self.ticks += 1
        self._last_tick_ms = tick_ms
        self._queue_depth = queue_depth
        if self.tick_budget_ms and tick_ms > self.tick_budget_ms:
            self._busts += 1
            self._healthy_ticks = 0
            if (
                self._busts >= self.deadline_k
                and (self._busts - self.deadline_k) % self.deadline_k == 0
            ):
                self._degrade_tier()
        else:
            self._busts = 0
            if self.degraded():
                self._healthy_ticks += 1
                if self._healthy_ticks >= self.recover_ticks:
                    self._healthy_ticks = 0
                    self._restore_tier()
        self._evaluate()

    def note_idle(self, queue_depth: int = 0) -> None:
        """An empty flush window counts as an in-budget tick — the
        path back to OK once load drops."""
        self.note_tick(0.0, queue_depth)

    def note_queue_depth(self, depth: int) -> None:
        """Enqueue-path signal: escalate MID-window when a storm fills
        the queue, instead of one tick late. Cheap — the full
        evaluation runs only when the depth's pressure bucket changes
        (threshold crossings) or every 256 messages while it doesn't."""
        self._queue_depth = depth
        m = self.max_batch
        bucket = (depth >= m // 2) + (depth >= m) + (depth >= 2 * m)
        if bucket != self._depth_bucket or (depth & 0xFF) == 0:
            self._depth_bucket = bucket
            self._evaluate()

    async def run(self) -> None:
        """Sampler loop for tickerless (immediate-mode) servers — the
        lag/RSS signals still need a clock. Supervised by the server;
        never spawned when a ticker drives ``note_tick``."""
        while True:
            await asyncio.sleep(self.sample_interval)
            self.note_idle(self._queue_depth)

    def _signal_level(self, value: float, enters: tuple) -> int:
        """Level this signal votes for, with exit thresholds at
        ``hysteresis`` × enter for every level at/below the current
        state — the anti-flap asymmetry."""
        cur = _LEVEL[self._state]
        level = 0
        for i, enter in enumerate(enters, start=1):
            threshold = enter * self.hysteresis if i <= cur else enter
            if value >= threshold:
                level = i
        return level

    def _raw_level(self) -> int:
        level = self._signal_level(
            self._queue_depth / self.max_batch, _QUEUE_FRAC
        )
        if self.tick_budget_ms and self._busts >= self.deadline_k:
            # a single slow tick is noise; K consecutive busts are load
            level = max(level, self._signal_level(
                self._last_tick_ms / self.tick_budget_ms, _TICK_RATIO
            ))
        if self.loop_monitor is not None:
            level = max(level, self._signal_level(
                self.loop_monitor.last_lag_ms, _LAG_MS
            ))
        if self._rss_limit_bytes:
            now = self._clock()
            if now - self._rss_read_at > 0.2:  # bound the /proc reads
                self._rss_bytes = read_rss_bytes()
                self._rss_read_at = now
            level = max(level, self._signal_level(
                self._rss_bytes / self._rss_limit_bytes, _RSS_FRAC
            ))
        return level

    def _evaluate(self) -> None:
        forced = failpoints.forced(FORCE_STATE_FAILPOINT)
        if forced is not None:
            forced = forced.lower()
            if forced in _LEVEL:
                self._recover = 0
                self._transition(forced, "failpoint")
            else:
                logger.warning(
                    "overload.force_state failpoint carries unknown "
                    "state %r — ignored", forced,
                )
            return
        raw = self._raw_level()
        cur = _LEVEL[self._state]
        if raw > cur:
            self._recover = 0
            self._transition(STATES[raw], "signal")
        elif raw < cur:
            self._recover += 1
            if self._recover >= self.recover_ticks:
                self._recover = 0
                self._transition(STATES[cur - 1], "recovered")
        else:
            self._recover = 0

    def _transition(self, state: str, reason: str) -> None:
        if state == self._state:
            return
        old = self._state
        self._state = state
        self.transitions += 1
        if _LEVEL[state] > self.peak_level:
            self.peak_level = _LEVEL[state]
        if self.metrics is not None:
            self.metrics.inc("overload.transitions")
        log = (
            logger.warning if _LEVEL[state] > _LEVEL[old] else logger.info
        )
        log(
            "overload governor %s -> %s (%s; tick %.1f ms / budget "
            "%.1f ms, queue %d/%d, busts %d)",
            old, state, reason, self._last_tick_ms, self.tick_budget_ms,
            self._queue_depth, self.max_batch, self._busts,
        )

    def _degrade_tier(self) -> None:
        admitted = max(self.min_batch, self._admitted // 2)
        if admitted == self._admitted:
            return
        self._admitted = admitted
        self.tier_degradations += 1
        if self.metrics is not None:
            self.metrics.inc("overload.tier_degradations")
        logger.warning(
            "tick deadline busted %d consecutive times (budget %.1f ms)"
            " — admitted batch tier shrunk to %d",
            self._busts, self.tick_budget_ms, admitted,
        )

    def _restore_tier(self) -> None:
        self._admitted = min(self.max_batch, self._admitted * 2)
        if self._admitted == self.max_batch:
            self._frame_parity = False
            logger.info(
                "tick deadline recovered — admitted batch tier back to "
                "full service (%d)", self.max_batch,
            )

    # endregion

    # region: admission

    def classify(self, instruction, is_entity: bool) -> str:
        if is_entity:
            return CLASS_ENTITY
        return _CLASS_OF.get(instruction, CLASS_CONTROL)

    def admit(self, instruction, sender, is_entity: bool = False) -> bool:
        """One inbound message's admission decision (the router's
        choke point). False = shed, already counted — the caller just
        drops the message."""
        cls = self.classify(instruction, is_entity)
        if cls == CLASS_CONTROL:
            return True  # liveness survives overload
        if (
            self.peer_rate > 0
            and sender is not None
            and sender.int != 0  # NIL: server-internal injection (HTTP)
            and not self._take_token(sender)
            and cls != CLASS_RECORD  # records consume but never drop
        ):
            self._note_limited(sender, cls)
            return False
        if cls in (CLASS_RECORD, CLASS_ENTITY, CLASS_SUBSCRIBE):
            # records are durable+acked (never shed); entity updates
            # shed by COALESCING in the plane (lossless); subscription
            # ops are control-plane index mutations
            return True
        if self._state == REJECT:
            self.shed[cls] += 1
            if self.metrics is not None:
                self.metrics.inc(f"overload.shed_{cls}")
            return False
        # locals in SHED_* shed drop-oldest at the ticker queue, not
        # here — the newest query is the freshest work
        return True

    def admit_handshake(self, resume: bool = False) -> tuple[bool, int]:
        """One inbound handshake's admission decision (the transports'
        choke point, BEFORE any connect-back/socket work). Returns
        ``(admitted, retry_after_ms)`` — the hint is 0 when admitted,
        jittered when refused so a refused storm de-synchronizes
        instead of re-arriving as one wave.

        New connects shed before resumes: a fresh peer costs full
        registration (index rows, entity slots, connect-back socket)
        while a resume rebinds state the server is ALREADY paying for
        — refusing resumes leaks exactly the memory the TTL bounds.
        So new connects shed at SHED_HIGH and above; resumes pass in
        every state below REJECT, and in REJECT a token bucket
        (``resume_rate``/s) keeps admitting a bounded trickle so a
        mass reconnect drains rather than livelocking."""
        level = _LEVEL[self._state]
        if resume:
            if level < _LEVEL[REJECT] or self._take_resume_token():
                self.handshakes_admitted += 1
                return True, 0
            cls = CLASS_HS_RESUME
        else:
            if level < _LEVEL[SHED_HIGH]:
                self.handshakes_admitted += 1
                return True, 0
            cls = CLASS_HS_NEW
        self.shed[cls] += 1
        if self.metrics is not None:
            self.metrics.inc(f"overload.shed_{cls}")
        return False, self._retry_after_ms()

    def _take_resume_token(self) -> bool:
        if self.resume_rate <= 0:
            return False
        now = self._clock()
        bucket = self._resume_bucket
        tokens = bucket[0] + (now - bucket[1]) * self.resume_rate
        bucket[0] = min(tokens, float(self.resume_burst))
        bucket[1] = now
        if bucket[0] >= 1.0:
            bucket[0] -= 1.0
            return True
        return False

    def _retry_after_ms(self) -> int:
        """Jittered backoff hint scaled to the governor state: the
        deeper the overload, the longer the herd is told to stay
        away. Uniform jitter in [0.5x, 1.5x) of the base."""
        base = 250 * (1 << max(0, _LEVEL[self._state] - 1))
        return max(1, int(base * (0.5 + self._jitter.random())))

    def take_refusal_hint(self) -> bool:
        """Budget for SENDING a refusal hint where it costs a socket
        (the ZMQ connect-back): a bounded trickle of hints beats both
        silence (clients retry blind at full rate) and an unbounded
        reflector (the refusal path DoSing the refuser)."""
        now = self._clock()
        bucket = self._hint_bucket
        bucket[0] = min(bucket[0] + (now - bucket[1]) * 50.0, 50.0)
        bucket[1] = now
        if bucket[0] >= 1.0:
            bucket[0] -= 1.0
            return True
        return False

    def coalesce_entities(self) -> bool:
        """SHED_LOW and above: the EntityPlane stages updates of live
        entities last-write-wins per uuid and applies them once per
        tick (lossless for position streams)."""
        return self._state != OK

    def local_queue_cap(self) -> int:
        """Hard bound on the ticker's pending queue; beyond it the
        OLDEST queued LocalMessage is dropped (counted). 2 × max_batch:
        one full tick in flight plus one accumulating."""
        return 2 * self.max_batch

    def note_drop_oldest(self) -> None:
        self.drop_oldest += 1
        if self.metrics is not None:
            self.metrics.inc("overload.drop_oldest")

    def take_frame_skip(self) -> bool:
        """While the tier is degraded, skip the entity neighbor-frame
        fan-out every OTHER tick (positions/index still advance every
        tick — only the delivery leg halves)."""
        if not self.degraded():
            return False
        self._frame_parity = not self._frame_parity
        return self._frame_parity

    # endregion

    # region: per-peer token buckets

    def _take_token(self, sender) -> bool:
        now = self._clock()
        bucket = self._buckets.get(sender)
        if bucket is None:
            bucket = self._buckets[sender] = [float(self.peer_burst), now, 0]
        else:
            tokens = bucket[0] + (now - bucket[1]) * self.peer_rate
            bucket[0] = (
                float(self.peer_burst)
                if tokens > self.peer_burst else tokens
            )
            bucket[1] = now
        if bucket[0] >= 1.0:
            bucket[0] -= 1.0
            bucket[2] = 0
            return True
        bucket[2] += 1
        return False

    def _note_limited(self, sender, cls: str) -> None:
        self.rate_limited += 1
        if cls in self.shed:
            self.shed[cls] += 1
        if self.metrics is not None:
            self.metrics.inc("peers.rate_limited")
            if cls in self.shed:
                self.metrics.inc(f"overload.shed_{cls}")
        if not self.evict_after:
            return
        bucket = self._buckets.get(sender)
        if (
            bucket is not None
            and bucket[2] >= self.evict_after
            and sender not in self._evicting
            and self.on_evict is not None
        ):
            # sustained abuse: hand the uuid to the server's eviction
            # hook exactly once (the peer leaves through the normal
            # PeerMap.remove path, PeerDisconnect broadcast included)
            self._evicting.add(sender)
            logger.warning(
                "peer %s rate-limited %d consecutive messages — "
                "evicting", sender, bucket[2],
            )
            self.on_evict(sender)

    def forget_peer(self, sender) -> None:
        """Disconnect cleanup: drop the peer's bucket so the dict
        stays bounded by live peers."""
        self._buckets.pop(sender, None)
        self._evicting.discard(sender)

    # endregion

    def export_state(self) -> dict:
        """Cluster shed export (cluster/shard.py state packets): the
        compact view a router tier's :class:`~..cluster.router.
        ShedMirror` acts on — the level it mirrors for router-side
        admission plus the shed counters that close the cluster-wide
        exact-accounting audit (offered == admitted + shed-at-router +
        shed-at-shard, bench config 11)."""
        return {
            "level": self.level,
            "state": self._state,
            "admitted_batch": self._admitted,
            "shed": dict(self.shed),
            "drop_oldest": self.drop_oldest,
            "rate_limited": self.rate_limited,
        }

    def status(self) -> dict:
        """The ``overload`` gauge + the /healthz block. Numeric leaves
        flatten into Prometheus gauges."""
        return {
            "state": self._state,
            "state_level": _LEVEL[self._state],
            "peak_level": self.peak_level,
            "transitions": self.transitions,
            "admitted_batch": self._admitted,
            "tier_degraded": self.degraded(),
            "tier_degradations": self.tier_degradations,
            "consecutive_busts": self._busts,
            "tick_budget_ms": round(self.tick_budget_ms, 3),
            "last_tick_ms": round(self._last_tick_ms, 3),
            "queue_depth": self._queue_depth,
            "shed_local": self.shed[CLASS_LOCAL],
            "shed_global": self.shed[CLASS_GLOBAL],
            "shed_handshake_new": self.shed[CLASS_HS_NEW],
            "shed_handshake_resume": self.shed[CLASS_HS_RESUME],
            "handshakes_admitted": self.handshakes_admitted,
            "drop_oldest": self.drop_oldest,
            "rate_limited": self.rate_limited,
            "peers_tracked": len(self._buckets),
            "rss_mb": round(self._rss_bytes / (1 << 20), 1),
        }
