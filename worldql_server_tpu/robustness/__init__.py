"""Crash containment: fault injection, task supervision, degraded mode.

Three pillars (README "Fault injection & supervision"):

* :mod:`.failpoints` — named, near-zero-overhead-when-off fault
  injection at every boundary the server can lose work at;
* :mod:`.supervisor` — every long-lived task observed, restarted with
  backoff within a budget, escalated to clean shutdown when critical;
* :mod:`.resilient` — the spatial backend wrapper that contains device
  failures, rebuilds from the authoritative mirror, and fails over
  TPU→CPU so fan-out degrades instead of flatlining.

``resilient`` imports lazily via ``__getattr__``: it pulls in the
spatial package, which the failpoint call sites (wal, transports)
must not.
"""

from . import failpoints
from .supervisor import Supervisor, SupervisedTask, TaskPolicy

__all__ = [
    "failpoints",
    "Supervisor",
    "SupervisedTask",
    "TaskPolicy",
    "ResilientBackend",
]


def __getattr__(name):
    if name == "ResilientBackend":
        from .resilient import ResilientBackend

        return ResilientBackend
    raise AttributeError(name)
