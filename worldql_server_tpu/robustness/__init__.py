"""Crash containment: fault injection, task supervision, degraded mode.

Three pillars (README "Fault injection & supervision"):

* :mod:`.failpoints` — named, near-zero-overhead-when-off fault
  injection at every boundary the server can lose work at;
* :mod:`.supervisor` — every long-lived task observed, restarted with
  backoff within a budget, escalated to clean shutdown when critical;
* :mod:`.resilient` — the spatial backend wrapper that contains device
  failures, rebuilds from the authoritative mirror, and fails over
  TPU→CPU so fan-out degrades instead of flatlining;
* :mod:`.overload` — the load-survival plane: hysteretic
  ``OK → SHED_LOW → SHED_HIGH → REJECT`` admission governor,
  priority-classed shedding, per-peer token buckets, and
  tick-deadline degradation (README "Overload & admission control");
* :mod:`.sessions` — client-survival: a dropped peer's
  subscriptions/entities park for ``--session-ttl`` and a reconnect
  presenting the handshake-minted token rebinds with zero index churn
  (README "Sessions & scenarios").

``resilient`` and ``overload`` import lazily via ``__getattr__``:
they pull in the spatial/protocol packages, which the failpoint call
sites (wal, transports) must not.
"""

from . import failpoints
from .supervisor import Supervisor, SupervisedTask, TaskPolicy

__all__ = [
    "failpoints",
    "Supervisor",
    "SupervisedTask",
    "TaskPolicy",
    "ResilientBackend",
    "OverloadGovernor",
    "SessionStore",
]


def __getattr__(name):
    if name == "ResilientBackend":
        from .resilient import ResilientBackend

        return ResilientBackend
    if name == "OverloadGovernor":
        from .overload import OverloadGovernor

        return OverloadGovernor
    if name == "SessionStore":
        from .sessions import SessionStore

        return SessionStore
    raise AttributeError(name)
