"""Session continuity: park peer state across a dropped transport.

PR 4 made the *server* survive faults and PR 10 made it survive load;
this module makes the *clients* survive both. Without it, a dropped
connection destroys the peer's area/world subscriptions and owned
entity slots, so the one failure mode real deployments hit constantly
— a mass reconnect after a network blip — turns into a full
re-handshake/re-subscribe/re-register stampede at exactly the moment
the server is least able to absorb it (the retry-storm / metastable-
failure regime of the overload literature).

The contract, end to end:

* **Mint** — with ``--session-ttl`` > 0 every successful handshake
  mints a resumable session token (128-bit, ``secrets``), delivered in
  the handshake echo: ZeroMQ carries it as the echo ``parameter``
  (previously always None), WebSocket as ``flex`` on the server's
  UUID-assigning handshake. The token — not the guessable peer UUID —
  is the resume capability.
* **Park** — when the peer's transport drops (hard close, staleness
  sweep, failed send, worker loss), ``PeerMap.remove`` still runs:
  PeerDisconnect still broadcasts and transport/delivery socket state
  is still released, but the peer's *logical* state — subscription
  index rows, owned entity slots, governor bucket — is parked here
  instead of torn down. Frames addressed to a parked peer are counted
  (``undelivered``), never buffered: buffering disconnected peers'
  fan-out is an unbounded-memory vector.
* **Resume** — a reconnect presenting the token (ZMQ: handshake
  ``flex``; WS: echo ``flex``) atomically rebinds the new transport
  to the parked state: no index churn, no entity re-registration, and
  the new binding may land on a different delivery-plane shard. A
  resume is also legal while the stale old binding is still in the
  map (the server has not yet noticed the drop) — the old transport
  is detached silently, with no PeerDisconnect/PeerConnect churn.
* **Expire** — a supervised sweeper reclaims sessions parked longer
  than the TTL through the normal removal path (``on_expire`` →
  ``WorldQLServer._teardown_peer_state``), counted as
  ``peers.evicted_session_expired``. A fresh tokenless handshake for
  a parked UUID also tears the old state down first: without the
  token, same-UUID is a new peer, not a resume (anything else would
  make the UUID a hijackable capability).

``--session-ttl 0`` (the default) never constructs this class — every
handshake/disconnect path keeps today's behavior byte for byte.
"""

from __future__ import annotations

import asyncio
import logging
import secrets
import time
import uuid as uuid_mod
from typing import Callable

logger = logging.getLogger(__name__)


class Session:
    """One peer's resumable continuity record."""

    __slots__ = (
        "token", "uuid", "kind", "minted_at", "parked_at", "deadline",
        "resumes", "undelivered",
    )

    def __init__(self, token: str, uuid: uuid_mod.UUID, kind: str,
                 now: float):
        self.token = token
        self.uuid = uuid
        self.kind = kind
        self.minted_at = now
        #: None while the transport is bound; set at park time
        self.parked_at: float | None = None
        self.deadline: float = 0.0
        self.resumes = 0
        #: frames addressed to this peer while parked (counted, never
        #: buffered — accounting, not replay)
        self.undelivered = 0

    @property
    def parked(self) -> bool:
        return self.parked_at is not None


class SessionStore:
    """Token → parked-peer-state registry for one server. Event-loop
    owned (mutations happen in handshake/removal handlers and the
    sweeper, all on the loop)."""

    def __init__(
        self,
        ttl: float,
        *,
        metrics=None,
        on_expire: Callable[[uuid_mod.UUID], None] | None = None,
        on_undelivered: Callable[[uuid_mod.UUID], None] | None = None,
        sweep_interval: float | None = None,
        clock=time.monotonic,
    ):
        self.ttl = float(ttl)
        self.metrics = metrics
        self.on_expire = on_expire
        # Loss hook (--interest on): every frame that lands on a
        # parked session is a GAP in that peer's stream — the server
        # wires this to InterestManager.mark_resync so the first frame
        # after resume is a forced full, never an unappliable delta.
        self.on_undelivered = on_undelivered
        # sweep often enough that reclamation lag is a fraction of the
        # TTL, but never busy-spin tiny TTLs
        self.sweep_interval = (
            sweep_interval if sweep_interval is not None
            else max(0.05, min(self.ttl / 4.0, 5.0))
        )
        self._clock = clock
        self._by_token: dict[str, Session] = {}
        self._by_uuid: dict[uuid_mod.UUID, Session] = {}
        # counters (sessions gauge + /healthz block)
        self.minted = 0
        self.parked_total = 0
        self.resumed = 0
        self.expired = 0
        self.discarded = 0
        self.rejected_tokens = 0
        self.undelivered_frames = 0

    # region: lifecycle

    def mint(self, uuid: uuid_mod.UUID, kind: str) -> Session:
        """New session for a freshly handshaken peer. Replaces (and
        invalidates the token of) any prior session under the same
        UUID — one live session per peer."""
        old = self._by_uuid.pop(uuid, None)
        if old is not None:
            self._by_token.pop(old.token, None)
        session = Session(secrets.token_hex(16), uuid, kind, self._clock())
        self._by_token[session.token] = session
        self._by_uuid[uuid] = session
        self.minted += 1
        return session

    def get(self, uuid: uuid_mod.UUID) -> Session | None:
        return self._by_uuid.get(uuid)

    def peek(self, token, uuid: uuid_mod.UUID | None = None
             ) -> Session | None:
        """Validate a presented token WITHOUT consuming anything: the
        admission decision (resume class) happens before the rebind.
        ``uuid``, when given, must match the session's (ZMQ clients
        sign their own sender UUID; a token stolen cross-UUID is
        refused). Expired-but-unswept sessions refuse too."""
        if not token:
            return None
        if isinstance(token, (bytes, bytearray, memoryview)):
            try:
                token = bytes(token).decode("ascii")
            except UnicodeDecodeError:
                self.rejected_tokens += 1
                return None
        session = self._by_token.get(token)
        if session is None:
            self.rejected_tokens += 1
            return None
        if uuid is not None and session.uuid != uuid:
            self.rejected_tokens += 1
            return None
        if session.parked and self._clock() >= session.deadline:
            # past TTL but the sweeper hasn't run yet: not resumable
            # (the state is already condemned)
            self.rejected_tokens += 1
            return None
        return session

    def park(self, uuid: uuid_mod.UUID) -> bool:
        """The peer's transport dropped. True = a live session exists
        and its logical state is now parked (the caller must SKIP the
        index/entity teardown); False = no session, tear down as
        always."""
        session = self._by_uuid.get(uuid)
        if session is None:
            return False
        session.parked_at = self._clock()
        session.deadline = session.parked_at + self.ttl
        self.parked_total += 1
        if self.metrics is not None:
            self.metrics.inc("sessions.parked")
        logger.info(
            "session for %s parked (ttl %.1fs) — subscriptions and "
            "entities held for resume", uuid, self.ttl,
        )
        return True

    def resume(self, session: Session) -> Session:
        """Consume a successful rebind: the session (validated via
        :meth:`peek`) is live again under its original token."""
        session.parked_at = None
        session.deadline = 0.0
        session.resumes += 1
        self.resumed += 1
        if self.metrics is not None:
            self.metrics.inc("sessions.resumed")
        return session

    def discard(self, uuid: uuid_mod.UUID) -> Session | None:
        """Drop the session outright (full teardown happened or is
        about to): its token can never resume again."""
        session = self._by_uuid.pop(uuid, None)
        if session is not None:
            self._by_token.pop(session.token, None)
            self.discarded += 1
        return session

    # endregion

    # region: migration (live resharding)

    def export_parked(self, uuids) -> list[dict]:
        """Serialize the PARKED sessions among ``uuids`` for a world
        migration. Tokens ride along verbatim — the resume capability
        must survive the move, or a mid-park migration silently
        orphans every affected client. Live (bound) sessions stay
        home: their transport is still attached to THIS process."""
        now = self._clock()
        rows = []
        for uuid in uuids:
            session = self._by_uuid.get(uuid)
            if session is None or not session.parked:
                continue
            rows.append({
                "token": session.token,
                "uuid": session.uuid.hex,
                "kind": session.kind,
                "remaining_s": max(0.0, session.deadline - now),
                "resumes": session.resumes,
                "undelivered": session.undelivered,
            })
        return rows

    def import_parked(self, rows: list[dict]) -> list[uuid_mod.UUID]:
        """Adopt migrated parked sessions under their ORIGINAL tokens.
        The TTL continues from where the source left it (remaining
        time, not a fresh ``self.ttl`` — migration must not extend the
        reclamation deadline). Returns the adopted UUIDs so the caller
        can funnel each through ``mark_resync``."""
        now = self._clock()
        adopted = []
        for row in rows:
            try:
                uuid = uuid_mod.UUID(hex=row["uuid"])
                token = str(row["token"])
                kind = str(row.get("kind", "unknown"))
                remaining = float(row.get("remaining_s", self.ttl))
            except (KeyError, TypeError, ValueError):
                continue
            old = self._by_uuid.pop(uuid, None)
            if old is not None:
                self._by_token.pop(old.token, None)
            session = Session(token, uuid, kind, now)
            session.parked_at = now
            session.deadline = now + max(0.0, remaining)
            session.resumes = int(row.get("resumes", 0))
            session.undelivered = int(row.get("undelivered", 0))
            self._by_token[token] = session
            self._by_uuid[uuid] = session
            self.parked_total += 1
            adopted.append(uuid)
        return adopted

    # endregion

    # region: accounting + sweep

    def note_undelivered(self, uuid: uuid_mod.UUID) -> None:
        """A fan-out frame addressed a parked peer: counted, never
        buffered (PeerMap delivery path)."""
        session = self._by_uuid.get(uuid)
        if session is not None and session.parked:
            session.undelivered += 1
            self.undelivered_frames += 1
            if self.on_undelivered is not None:
                self.on_undelivered(uuid)

    def expire_due(self) -> list[uuid_mod.UUID]:
        """One reclamation pass: every parked session past its
        deadline leaves through ``on_expire`` (the server's normal
        teardown). Returns the reclaimed UUIDs."""
        now = self._clock()
        due = [
            s for s in self._by_uuid.values()
            if s.parked and now >= s.deadline
        ]
        reclaimed = []
        for session in due:
            self.discard(session.uuid)
            self.expired += 1
            if self.metrics is not None:
                self.metrics.inc("peers.evicted_session_expired")
            logger.info(
                "session for %s expired after %.1fs parked — "
                "reclaiming subscriptions and entities",
                session.uuid, self.ttl,
            )
            if self.on_expire is not None:
                try:
                    self.on_expire(session.uuid)
                except Exception:
                    logger.exception(
                        "session-expiry teardown failed for %s — "
                        "continuing the sweep", session.uuid,
                    )
            reclaimed.append(session.uuid)
        return reclaimed

    async def sweep(self) -> None:
        """Supervised sweeper loop (``session-sweep``): reclamation
        must survive a raising teardown hook and keep sweeping."""
        while True:
            await asyncio.sleep(self.sweep_interval)
            self.expire_due()

    # endregion

    def parked_count(self) -> int:
        return sum(1 for s in self._by_uuid.values() if s.parked)

    def stats(self) -> dict:
        """The ``sessions`` gauge + the /healthz block."""
        return {
            "ttl_s": self.ttl,
            "live": len(self._by_uuid),
            "parked": self.parked_count(),
            "minted": self.minted,
            "parked_total": self.parked_total,
            "resumed": self.resumed,
            "expired": self.expired,
            "discarded": self.discarded,
            "rejected_tokens": self.rejected_tokens,
            "undelivered_frames": self.undelivered_frames,
        }
