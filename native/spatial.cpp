// Native query preparation: fused cube quantization + double spatial
// hash in one pass over a position batch — the per-tick host-side cost
// of the fan-out engine (Python twins: worldql_server_tpu/spatial/
// quantize.coord_clamp_batch and hashing.spatial_keys/spatial_keys2).
//
// Semantics are bit-exact with the golden quantizer (reference:
// worldql_server/src/subscriptions/cube_area.rs:23-44): max-corner
// labeling, sign symmetry, 0 -> +size, exact multiples label their own
// cube, NaN -> +size, +-inf -> +-i64::MAX, Rust-style saturating f64 ->
// i64 casts. The hash is the splitmix64 chain from spatial/hashing.py.
//
// C ABI (ctypes consumer: worldql_server_tpu/spatial/native_keys.py):
//   wql_spatial_abi() -> 1
//   wql_query_keys(pos[n*3] f64, world_ids[n] i32, n, cube_size,
//                  seed1, seed2, keys1[n] i64 out, keys2[n] i64 out)
//   wql_encode_queries(pos[n*3] f64, world_ids[n] i32, senders[n] i32,
//                      repls[n] i8, n, cap, cube_size, seed1, seed2,
//                      keys1[cap] i64 out, keys2[cap] i64 out,
//                      senders_out[cap] i32, repls_out[cap] i8)
//     — the fused batch encode: quantize + both hashes + capacity-tier
//     padding straight into the dispatch-ready layout, one pass, no
//     Python-side intermediates (ctypes releases the GIL for the call).
//     Padding lanes mirror spatial/hashing.py: key1 = PAD_KEY
//     (2^63 - 1), key2 = QUERY_PAD_KEY2 (1), sender = -1, repl = 0 —
//     parity with the numpy twin is pinned lane-for-lane by
//     tests/test_native_keys.py, padding included.

#include <chrono>
#include <cmath>
#include <cstdint>
#include <random>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t I64_MAX = INT64_MAX;
constexpr int64_t I64_MIN = INT64_MIN;
// float(2^63 - 1) == float(2^63): both bounds have this magnitude
constexpr double I64_MAX_F = 9223372036854775808.0;

// Rust `f64 as i64`: NaN -> 0, out-of-range saturates.
inline int64_t sat_i64(double f) {
  if (std::isnan(f)) return 0;
  if (f >= I64_MAX_F) return I64_MAX;
  if (f <= -I64_MAX_F) return I64_MIN;
  return static_cast<int64_t>(f);
}

// Python twin: quantize.coord_clamp (scalar reference semantics).
inline int64_t coord_clamp(double coord, int64_t size) {
  if (std::isinf(coord)) return coord > 0 ? I64_MAX : -I64_MAX;

  const double size_f = static_cast<double>(size);
  const double abs_c = std::fabs(coord);
  const int64_t mult = (coord < 0.0) ? -1 : 1;  // NaN compares false -> +1

  if (!std::isnan(coord)) {
    if (std::fmod(abs_c, size_f) == 0.0 && coord != 0.0) {
      return sat_i64(coord);
    }
  }

  double rounded = std::ceil(abs_c / size_f) * size_f;
  if (abs_c == 0.0) rounded = size_f;  // round_by_multiple: 0 -> size

  int64_t result;
  if (rounded > coord) {  // NaN > NaN false -> falls to +size, like Rust
    result = sat_i64(rounded);
  } else {
    const int64_t ri = sat_i64(rounded);
    result = (ri > I64_MAX - size) ? I64_MAX : ri + size;  // saturating
  }
  return result * mult;
}

// splitmix64 mixer — constants shared with spatial/hashing.py.
inline uint64_t mix(uint64_t x) {
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

constexpr uint64_t GOLDEN = 0x9E3779B97F4A7C15ULL;

inline int64_t chain(uint64_t h, uint64_t w, uint64_t cx, uint64_t cy,
                     uint64_t cz) {
  h = mix(h ^ w);
  h = mix(h ^ cx);
  h = mix(h ^ cy);
  h = mix(h ^ cz);
  return static_cast<int64_t>(h);
}

}  // namespace

extern "C" {

int64_t wql_spatial_abi() { return 1; }

void wql_query_keys(const double* pos, const int32_t* world_ids, int64_t n,
                    int64_t cube_size, uint64_t seed1, uint64_t seed2,
                    int64_t* keys1, int64_t* keys2) {
  const uint64_t h1 = mix(seed1 + GOLDEN);
  const uint64_t h2 = mix(seed2 + GOLDEN);
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t cx =
        static_cast<uint64_t>(coord_clamp(pos[3 * i + 0], cube_size));
    const uint64_t cy =
        static_cast<uint64_t>(coord_clamp(pos[3 * i + 1], cube_size));
    const uint64_t cz =
        static_cast<uint64_t>(coord_clamp(pos[3 * i + 2], cube_size));
    // world id sign-extends i32 -> i64 before the bit view, like
    // world_ids.astype(int64).view(uint64) in the numpy twin
    const uint64_t w =
        static_cast<uint64_t>(static_cast<int64_t>(world_ids[i]));
    keys1[i] = chain(h1, w, cx, cy, cz);
    keys2[i] = chain(h2, w, cx, cy, cz);
  }
}

// hashing.py twins: PAD_KEY / QUERY_PAD_KEY2 (see header comment)
constexpr int64_t PAD_KEY = INT64_MAX;
constexpr int64_t QUERY_PAD_KEY2 = 1;

void wql_encode_queries(const double* pos, const int32_t* world_ids,
                        const int32_t* senders, const int8_t* repls,
                        int64_t n, int64_t cap, int64_t cube_size,
                        uint64_t seed1, uint64_t seed2, int64_t* keys1,
                        int64_t* keys2, int32_t* senders_out,
                        int8_t* repls_out) {
  wql_query_keys(pos, world_ids, n, cube_size, seed1, seed2, keys1, keys2);
  for (int64_t i = 0; i < n; ++i) {
    senders_out[i] = senders[i];
    repls_out[i] = repls[i];
  }
  for (int64_t i = n; i < cap; ++i) {
    keys1[i] = PAD_KEY;
    keys2[i] = QUERY_PAD_KEY2;
    senders_out[i] = -1;
    repls_out[i] = 0;
  }
}

// ------------------------------------------------------------------
// wql_areamap_probe: a reference-class CPU hot path (ROADMAP 5a).
//
// Micro-port of the reference implementation's AreaMap lookup (the
// Rust server's HashMap<cube, Vec<peer>> per world,
// worldql_server/src/subscriptions/area_map.rs): build a hash map of
// n_subs subscriptions keyed by quantized cube, then resolve
// n_queries point lookups against it. The timing this returns is the
// calibration row `vs_reference` in the bench JSON — what a
// reference-shaped single-threaded native CPU path achieves on THIS
// machine at the same shapes — so `vs_baseline` (measured against our
// own Python oracle) stops grading our own homework. Lookup only: no
// fan-out assembly, no serialization, no transport — i.e. a FLOOR for
// the reference's per-query cost, deliberately generous to it.
//
//   out[0] = build wall in ms
//   out[1] = lookup wall in ns per query
//   out[2] = total peer rows matched (also defeats dead-code elim)
//
// Uses coord_clamp — the golden quantizer both engines share — so
// probe and engine resolve identical cube geometry.

int64_t wql_areamap_probe(int64_t n_subs, int64_t n_queries,
                          int64_t cube_size, uint64_t seed, double* out) {
  if (n_subs <= 0 || n_queries <= 0 || cube_size <= 0) return -1;
  using clk = std::chrono::steady_clock;

  struct KeyHash {
    size_t operator()(uint64_t k) const {
      return static_cast<size_t>(mix(k));
    }
  };
  // cube triple -> one u64 key via the same splitmix chain the engine
  // hashes with (h1 fixed): collision-free enough for a probe and
  // cheaper than a 3-int struct key — again generous to the reference
  const uint64_t h1 = mix(seed + GOLDEN);
  std::mt19937_64 rng(seed);
  std::uniform_real_distribution<double> span(-4000.0, 4000.0);

  std::unordered_map<uint64_t, std::vector<int32_t>, KeyHash> areamap;
  areamap.reserve(static_cast<size_t>(n_subs));

  const auto t0 = clk::now();
  for (int64_t i = 0; i < n_subs; ++i) {
    const uint64_t cx =
        static_cast<uint64_t>(coord_clamp(span(rng), cube_size));
    const uint64_t cy =
        static_cast<uint64_t>(coord_clamp(span(rng), cube_size));
    const uint64_t cz =
        static_cast<uint64_t>(coord_clamp(span(rng), cube_size));
    const uint64_t key =
        static_cast<uint64_t>(chain(h1, 0, cx, cy, cz));
    areamap[key].push_back(static_cast<int32_t>(i & 0x3FF));
  }
  const auto t1 = clk::now();

  int64_t matched = 0;
  for (int64_t q = 0; q < n_queries; ++q) {
    const uint64_t cx =
        static_cast<uint64_t>(coord_clamp(span(rng), cube_size));
    const uint64_t cy =
        static_cast<uint64_t>(coord_clamp(span(rng), cube_size));
    const uint64_t cz =
        static_cast<uint64_t>(coord_clamp(span(rng), cube_size));
    const uint64_t key =
        static_cast<uint64_t>(chain(h1, 0, cx, cy, cz));
    const auto it = areamap.find(key);
    if (it != areamap.end()) {
      matched += static_cast<int64_t>(it->second.size());
    }
  }
  const auto t2 = clk::now();

  const double build_ms =
      std::chrono::duration<double, std::milli>(t1 - t0).count();
  const double lookup_ns =
      std::chrono::duration<double, std::nano>(t2 - t1).count() /
      static_cast<double>(n_queries);
  out[0] = build_ms;
  out[1] = lookup_ns;
  out[2] = static_cast<double>(matched);
  return 0;
}

}  // extern "C"
