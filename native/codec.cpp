// Native WorldQL wire codec: hand-rolled FlatBuffers reader/writer for
// the fixed WorldQLFB schema (reference: worldql_server/src/flatbuffers/
// WorldQLFB_generated.rs; Python twin: worldql_server_tpu/protocol/codec.py).
//
// The reader treats input as untrusted: every load is bounds-checked
// against the buffer (the Rust reference relies on flatbuffers verifier
// semantics; the Python twin bounds-checks likewise). The writer emits
// canonical back-to-front FlatBuffers with per-table vtables (no dedup —
// slightly larger buffers, identical semantics).
//
// C ABI (ctypes consumer: worldql_server_tpu/protocol/native_codec.py):
//   wql_decode(buf, len, WqlMsg* out) -> 0 ok / negative error
//   wql_encode(const WqlMsg* in, uint8_t** out, size_t* out_len) -> 0 ok
//   wql_buffer_free(uint8_t*)
// Strings/bytes in WqlMsg are (pointer, length) views; on decode they
// point into the caller's input buffer (zero-copy).

#include <algorithm>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <vector>

constexpr int32_t WQL_MAX_OBJS = 1024;  // per-message record/entity cap

typedef struct {
  const uint8_t* uuid;  int32_t uuid_len;
  const uint8_t* world; int32_t world_len;
  const uint8_t* data;  int32_t data_len;   // data == NULL → absent
  const uint8_t* flex;  int32_t flex_len;   // flex == NULL → absent
  double x, y, z;
  uint8_t has_pos;
} WqlObj;

typedef struct {
  uint8_t instruction;
  uint8_t replication;
  uint8_t has_pos;
  double x, y, z;
  const uint8_t* parameter; int32_t parameter_len;  // NULL → absent
  const uint8_t* sender;    int32_t sender_len;     // NULL → absent
  const uint8_t* world;     int32_t world_len;      // NULL → absent
  const uint8_t* flex;      int32_t flex_len;       // NULL → absent
  int32_t n_records;
  int32_t n_entities;
  WqlObj records[WQL_MAX_OBJS];
  WqlObj entities[WQL_MAX_OBJS];
} WqlMsg;

enum {
  WQL_OK = 0,
  WQL_E_BOUNDS = -1,    // malformed/truncated buffer
  WQL_E_TOO_MANY = -2,  // > WQL_MAX_OBJS records or entities
  WQL_E_ALLOC = -3,
  WQL_E_CAPACITY = -4,  // entity columns too small — caller grows + retries
};

// ---------------------------------------------------------------- reader

namespace {

struct Reader {
  const uint8_t* buf;
  size_t len;

  bool in(size_t pos, size_t n) const {
    return pos <= len && n <= len - pos;
  }
  template <typename T>
  bool load(size_t pos, T* out) const {
    if (!in(pos, sizeof(T))) return false;
    std::memcpy(out, buf + pos, sizeof(T));
    return true;
  }
};

// Field position for a vtable slot; 0 if absent/malformed-absent.
static size_t field_pos(const Reader& r, size_t table, int slot, bool* err) {
  int32_t soff;
  if (!r.load<int32_t>(table, &soff)) { *err = true; return 0; }
  // vtable = table - soff (soffset may be negative)
  int64_t vt = static_cast<int64_t>(table) - soff;
  if (vt < 0 || !r.in(static_cast<size_t>(vt), 4)) { *err = true; return 0; }
  uint16_t vt_size;
  if (!r.load<uint16_t>(static_cast<size_t>(vt), &vt_size)) { *err = true; return 0; }
  size_t entry = static_cast<size_t>(vt) + 4 + 2 * static_cast<size_t>(slot);
  if (4 + 2 * (slot + 1) > vt_size) return 0;  // slot beyond vtable → default
  uint16_t foff;
  if (!r.load<uint16_t>(entry, &foff)) { *err = true; return 0; }
  if (foff == 0) return 0;
  size_t pos = table + foff;
  if (pos >= r.len) { *err = true; return 0; }
  return pos;
}

// Follow a uoffset32 at pos → target position.
static size_t indirect(const Reader& r, size_t pos, bool* err) {
  uint32_t uoff;
  if (!r.load<uint32_t>(pos, &uoff)) { *err = true; return 0; }
  size_t target = pos + uoff;
  if (target >= r.len) { *err = true; return 0; }
  return target;
}

// String/byte-vector at slot: view into the buffer.
static bool read_blob(const Reader& r, size_t table, int slot,
                      const uint8_t** out, int32_t* out_len, bool* err) {
  *out = nullptr; *out_len = 0;
  size_t fpos = field_pos(r, table, slot, err);
  if (*err || fpos == 0) return fpos != 0 && !*err;
  size_t s = indirect(r, fpos, err);
  if (*err) return false;
  uint32_t n;
  if (!r.load<uint32_t>(s, &n)) { *err = true; return false; }
  if (n > r.len || !r.in(s + 4, n)) { *err = true; return false; }
  *out = r.buf + s + 4;
  *out_len = static_cast<int32_t>(n);
  return true;
}

static uint8_t read_u8(const Reader& r, size_t table, int slot,
                       uint8_t dflt, bool* err) {
  size_t fpos = field_pos(r, table, slot, err);
  if (*err || fpos == 0) return dflt;
  uint8_t v;
  if (!r.load<uint8_t>(fpos, &v)) { *err = true; return dflt; }
  return v;
}

static bool read_vec3(const Reader& r, size_t table, int slot,
                      double* x, double* y, double* z, bool* err) {
  size_t fpos = field_pos(r, table, slot, err);
  if (*err || fpos == 0) return false;
  double v[3];
  if (!r.in(fpos, 24)) { *err = true; return false; }
  std::memcpy(v, r.buf + fpos, 24);
  *x = v[0]; *y = v[1]; *z = v[2];
  return true;
}

enum { OBJ_UUID = 0, OBJ_POSITION = 1, OBJ_WORLD = 2, OBJ_DATA = 3,
       OBJ_FLEX = 4 };
enum { MSG_INSTRUCTION = 0, MSG_PARAMETER = 1, MSG_SENDER = 2,
       MSG_WORLD = 3, MSG_REPLICATION = 4, MSG_RECORDS = 5,
       MSG_ENTITIES = 6, MSG_POSITION = 7, MSG_FLEX = 8 };

static bool read_obj(const Reader& r, size_t table, WqlObj* o, bool* err) {
  std::memset(o, 0, sizeof(WqlObj));
  read_blob(r, table, OBJ_UUID, &o->uuid, &o->uuid_len, err);
  if (*err) return false;
  read_blob(r, table, OBJ_WORLD, &o->world, &o->world_len, err);
  if (*err) return false;
  read_blob(r, table, OBJ_DATA, &o->data, &o->data_len, err);
  if (*err) return false;
  read_blob(r, table, OBJ_FLEX, &o->flex, &o->flex_len, err);
  if (*err) return false;
  o->has_pos = read_vec3(r, table, OBJ_POSITION, &o->x, &o->y, &o->z, err)
                   ? 1 : 0;
  return !*err;
}

static int read_obj_vector(const Reader& r, size_t table, int slot,
                           WqlObj* out, int32_t* out_n, bool* err) {
  *out_n = 0;
  size_t fpos = field_pos(r, table, slot, err);
  if (*err) return WQL_E_BOUNDS;
  if (fpos == 0) return WQL_OK;
  size_t vec = indirect(r, fpos, err);
  if (*err) return WQL_E_BOUNDS;
  uint32_t n;
  if (!r.load<uint32_t>(vec, &n)) return WQL_E_BOUNDS;
  if (n > WQL_MAX_OBJS) return WQL_E_TOO_MANY;
  if (!r.in(vec + 4, static_cast<size_t>(n) * 4)) return WQL_E_BOUNDS;
  for (uint32_t i = 0; i < n; i++) {
    size_t t = indirect(r, vec + 4 + 4 * i, err);
    if (*err) return WQL_E_BOUNDS;
    if (!read_obj(r, t, &out[i], err)) return WQL_E_BOUNDS;
  }
  *out_n = static_cast<int32_t>(n);
  return WQL_OK;
}

}  // namespace

extern "C" int wql_decode(const uint8_t* buf, size_t len, WqlMsg* out) {
  Reader r{buf, len};
  bool err = false;
  std::memset(out, 0, offsetof(WqlMsg, records));
  out->n_records = 0;
  out->n_entities = 0;

  uint32_t root_off;
  if (!r.load<uint32_t>(0, &root_off) || root_off >= len) return WQL_E_BOUNDS;
  size_t root = root_off;

  out->instruction = read_u8(r, root, MSG_INSTRUCTION, 0, &err);
  if (err) return WQL_E_BOUNDS;
  out->replication = read_u8(r, root, MSG_REPLICATION, 0, &err);
  if (err) return WQL_E_BOUNDS;
  read_blob(r, root, MSG_PARAMETER, &out->parameter, &out->parameter_len, &err);
  if (err) return WQL_E_BOUNDS;
  read_blob(r, root, MSG_SENDER, &out->sender, &out->sender_len, &err);
  if (err) return WQL_E_BOUNDS;
  read_blob(r, root, MSG_WORLD, &out->world, &out->world_len, &err);
  if (err) return WQL_E_BOUNDS;
  read_blob(r, root, MSG_FLEX, &out->flex, &out->flex_len, &err);
  if (err) return WQL_E_BOUNDS;
  out->has_pos = read_vec3(r, root, MSG_POSITION, &out->x, &out->y, &out->z,
                           &err) ? 1 : 0;
  if (err) return WQL_E_BOUNDS;

  int rc = read_obj_vector(r, root, MSG_RECORDS, out->records,
                           &out->n_records, &err);
  if (rc != WQL_OK || err) return rc != WQL_OK ? rc : WQL_E_BOUNDS;
  rc = read_obj_vector(r, root, MSG_ENTITIES, out->entities,
                       &out->n_entities, &err);
  if (rc != WQL_OK || err) return rc != WQL_OK ? rc : WQL_E_BOUNDS;
  return WQL_OK;
}

// ---------------------------------------------------------------- writer

namespace {

// Back-to-front FlatBuffers builder: offsets are measured from the END
// of the storage; final buffer is the tail slice.
struct Builder {
  std::vector<uint8_t> store;
  size_t head;       // index of first used byte
  size_t minalign = 1;

  explicit Builder(size_t cap = 1024) : store(cap), head(cap) {}

  size_t offset() const { return store.size() - head; }

  void grow(size_t need) {
    if (head >= need) return;
    size_t old_size = store.size();
    size_t new_size = old_size * 2;
    while (new_size - old_size + head < need) new_size *= 2;
    std::vector<uint8_t> bigger(new_size);
    std::memcpy(bigger.data() + (new_size - old_size), store.data(), old_size);
    head += new_size - old_size;
    store.swap(bigger);
  }

  void pad(size_t n) {
    grow(n);
    head -= n;
    std::memset(store.data() + head, 0, n);
  }

  // Align so that after writing `size` bytes, offset() % align == 0.
  void prep(size_t align, size_t extra) {
    if (align > minalign) minalign = align;
    size_t align_size = ((~(offset() + extra)) + 1) & (align - 1);
    pad(align_size);
  }

  void push(const void* src, size_t n) {
    grow(n);
    head -= n;
    std::memcpy(store.data() + head, src, n);
  }

  template <typename T>
  void push_scalar(T v) { push(&v, sizeof(T)); }

  // uoffset32 referencing an object at `target` (offset-from-end).
  void push_uoffset(size_t target) {
    prep(4, 0);
    uint32_t v = static_cast<uint32_t>(offset() + 4 - target);
    push_scalar<uint32_t>(v);
  }

  size_t create_blob(const uint8_t* data, size_t n, bool nul) {
    if (nul) { prep(4, n + 1); uint8_t z = 0; push(&z, 1); }
    else     { prep(4, n); }
    push(data, n);
    push_scalar<uint32_t>(static_cast<uint32_t>(n));
    return offset();
  }

  size_t create_vec3(double x, double y, double z) {
    prep(8, 24);
    double v[3] = {x, y, z};
    push(v, 24);
    return offset();
  }
};

struct TableBuilder {
  Builder& b;
  size_t start;                     // offset() at StartTable
  int max_slot = -1;
  size_t slot_off[16] = {0};        // field offset-from-end per slot

  explicit TableBuilder(Builder& b_) : b(b_), start(b_.offset()) {}

  void track(int slot) {
    slot_off[slot] = b.offset();
    if (slot > max_slot) max_slot = slot;
  }

  void field_u8(int slot, uint8_t v, uint8_t dflt) {
    if (v == dflt) return;
    b.prep(1, 0);
    b.push_scalar<uint8_t>(v);
    track(slot);
  }

  void field_uoffset(int slot, size_t target) {
    b.push_uoffset(target);
    track(slot);
  }

  void field_struct(int slot, size_t target) {
    // Structs are written immediately before; they must be inline at
    // the field position (flatbuffers invariant).
    (void)target;
    track(slot);
  }

  size_t end() {
    // soffset placeholder
    b.prep(4, 0);
    b.push_scalar<int32_t>(0);
    size_t table_start = b.offset();

    int n_slots = max_slot + 1;
    uint16_t vt_size = static_cast<uint16_t>(4 + 2 * n_slots);
    uint16_t tbl_size = static_cast<uint16_t>(table_start - start);

    // vtable entries, last slot first
    for (int i = n_slots - 1; i >= 0; i--) {
      uint16_t entry = slot_off[i]
          ? static_cast<uint16_t>(table_start - slot_off[i]) : 0;
      b.push_scalar<uint16_t>(entry);
    }
    b.push_scalar<uint16_t>(tbl_size);
    b.push_scalar<uint16_t>(vt_size);
    size_t vt = b.offset();

    // patch soffset: vtable relative to table
    int32_t soff = static_cast<int32_t>(vt - table_start);
    size_t table_pos = b.store.size() - table_start;
    std::memcpy(b.store.data() + table_pos, &soff, 4);
    return table_start;
  }
};

static size_t write_obj(Builder& b, const WqlObj* o) {
  size_t uuid_off = b.create_blob(o->uuid, o->uuid_len, true);
  size_t world_off = b.create_blob(o->world, o->world_len, true);
  size_t data_off = o->data ? b.create_blob(o->data, o->data_len, true) : 0;
  size_t flex_off = o->flex ? b.create_blob(o->flex, o->flex_len, false) : 0;

  TableBuilder t(b);
  t.field_uoffset(OBJ_UUID, uuid_off);
  if (o->has_pos) {
    b.create_vec3(o->x, o->y, o->z);
    t.field_struct(OBJ_POSITION, 0);
  }
  t.field_uoffset(OBJ_WORLD, world_off);
  if (data_off) t.field_uoffset(OBJ_DATA, data_off);
  if (flex_off) t.field_uoffset(OBJ_FLEX, flex_off);
  return t.end();
}

static size_t write_obj_vector(Builder& b, const WqlObj* objs, int32_t n) {
  std::vector<size_t> offs(n);
  for (int32_t i = 0; i < n; i++) offs[i] = write_obj(b, &objs[i]);
  b.prep(4, static_cast<size_t>(n) * 4);
  for (int32_t i = n - 1; i >= 0; i--) b.push_uoffset(offs[i]);
  b.push_scalar<uint32_t>(static_cast<uint32_t>(n));
  return b.offset();
}

}  // namespace

extern "C" int wql_encode(const WqlMsg* in, uint8_t** out, size_t* out_len) {
  if (in->n_records > WQL_MAX_OBJS || in->n_entities > WQL_MAX_OBJS)
    return WQL_E_TOO_MANY;
  Builder b(1024);

  size_t records_vec = in->n_records
      ? write_obj_vector(b, in->records, in->n_records) : 0;
  size_t entities_vec = in->n_entities
      ? write_obj_vector(b, in->entities, in->n_entities) : 0;

  size_t param_off = in->parameter
      ? b.create_blob(in->parameter, in->parameter_len, true) : 0;
  size_t sender_off = in->sender
      ? b.create_blob(in->sender, in->sender_len, true) : 0;
  size_t world_off = in->world
      ? b.create_blob(in->world, in->world_len, true) : 0;
  size_t flex_off = in->flex
      ? b.create_blob(in->flex, in->flex_len, false) : 0;

  TableBuilder t(b);
  t.field_u8(MSG_INSTRUCTION, in->instruction, 0);
  if (param_off) t.field_uoffset(MSG_PARAMETER, param_off);
  if (sender_off) t.field_uoffset(MSG_SENDER, sender_off);
  if (world_off) t.field_uoffset(MSG_WORLD, world_off);
  t.field_u8(MSG_REPLICATION, in->replication, 0);
  if (records_vec) t.field_uoffset(MSG_RECORDS, records_vec);
  if (entities_vec) t.field_uoffset(MSG_ENTITIES, entities_vec);
  if (in->has_pos) {
    b.create_vec3(in->x, in->y, in->z);
    t.field_struct(MSG_POSITION, 0);
  }
  if (flex_off) t.field_uoffset(MSG_FLEX, flex_off);
  size_t root = t.end();

  // root uoffset, padded to minalign
  b.prep(std::max<size_t>(b.minalign, 4), 4);
  b.push_uoffset(root);

  size_t n = b.offset();
  uint8_t* mem = static_cast<uint8_t*>(std::malloc(n));
  if (!mem) return WQL_E_ALLOC;
  std::memcpy(mem, b.store.data() + b.head, n);
  *out = mem;
  *out_len = n;
  return WQL_OK;
}

extern "C" void wql_buffer_free(uint8_t* p) { std::free(p); }

extern "C" int wql_max_objs(void) { return WQL_MAX_OBJS; }

// ------------------------------------------- columnar entity ingest
//
// The wire→SoA fast path (consumer: worldql_server_tpu/protocol/
// entity_wire.py → entities/ingest.py): batch-decode the `entities`
// lists of a whole recv batch straight into preallocated SoA columns —
// binary uuid keys, f32 positions/velocities — with zero per-entity
// Python objects. The entities vector is read directly off the wire
// (no WqlObj scratch), so this path has NO WQL_MAX_OBJS cap; its only
// bound is the caller's column capacity.
//
// A buffer is FAST (status 1) only when the whole message is a plain
// entity upsert batch the columnar path can represent: Local/Global-
// Message, no parameter (removals and exotic parameters keep their
// object-path semantics), canonical 36-char uuids, every entity world
// empty-or-equal to the message world, position present. Anything else
// is status 0 and the caller routes those bytes through the ordinary
// codec — identical semantics, slower.

namespace {

inline int hexval(uint8_t c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

// canonical 8-4-4-4-12 uuid string → 16 bytes; false for any other
// format (Python's uuid.UUID accepts more — those take the object path)
bool parse_uuid36(const uint8_t* s, int32_t len, uint8_t* out) {
  if (len != 36 || s[8] != '-' || s[13] != '-' || s[18] != '-' ||
      s[23] != '-')
    return false;
  static const int at[16] = {0,  2,  4,  6,  9,  11, 14, 16,
                             19, 21, 24, 26, 28, 30, 32, 34};
  for (int i = 0; i < 16; i++) {
    const int hi = hexval(s[at[i]]);
    const int lo = hexval(s[at[i] + 1]);
    if (hi < 0 || lo < 0) return false;
    out[i] = static_cast<uint8_t>((hi << 4) | lo);
  }
  return true;
}

constexpr uint8_t INSTR_GLOBAL_MESSAGE = 6;
constexpr uint8_t INSTR_LOCAL_MESSAGE = 7;

// Validate one Record/Entity table the way the object decoder would
// read it (uuid canonical + world present + every blob/struct in
// bounds) WITHOUT materializing anything. The fast path must never
// accept a buffer the object path would reject — corruption in a field
// the columnar consumer ignores (records, data) still routes slow.
bool validate_obj(const Reader& r, size_t table, bool* err) {
  const uint8_t* u; int32_t ulen;
  read_blob(r, table, OBJ_UUID, &u, &ulen, err);
  uint8_t scratch[16];
  if (*err || u == nullptr || !parse_uuid36(u, ulen, scratch)) return false;
  const uint8_t* w; int32_t wlen;
  read_blob(r, table, OBJ_WORLD, &w, &wlen, err);
  if (*err || w == nullptr) return false;
  const uint8_t* d; int32_t dlen;
  read_blob(r, table, OBJ_DATA, &d, &dlen, err);
  if (*err) return false;
  read_blob(r, table, OBJ_FLEX, &d, &dlen, err);
  if (*err) return false;
  double x, y, z;
  read_vec3(r, table, OBJ_POSITION, &x, &y, &z, err);
  return !*err;
}

}  // namespace

extern "C" int64_t wql_entities_abi(void) { return 1; }

// Decode a recv batch. Per buffer: status[i] = 1 (columnar entity
// batch; envelope + rows written) or 0 (route through the object
// path). Entity rows land at ent_start[i]..+ent_count[i] in the shared
// columns. Returns total rows written, or WQL_E_CAPACITY when ent_cap
// cannot hold them (caller doubles the columns and retries).
extern "C" int64_t wql_decode_entities(
    const uint8_t* const* bufs, const int64_t* lens, int64_t n_bufs,
    int8_t* status, uint8_t* instr_out, uint8_t* sender_key,
    int64_t* world_off, int32_t* world_len_out, int64_t* ent_start,
    int32_t* ent_count, int64_t ent_cap, uint8_t* uuid_keys,
    float* pos_out, float* vel_out, uint8_t* has_vel) {
  int64_t total = 0;
  for (int64_t bi = 0; bi < n_bufs; bi++) {
    status[bi] = 0;
    instr_out[bi] = 0;
    world_off[bi] = 0;
    world_len_out[bi] = 0;
    ent_start[bi] = total;
    ent_count[bi] = 0;

    Reader r{bufs[bi], static_cast<size_t>(lens[bi])};
    bool err = false;
    uint32_t root_off;
    if (!r.load<uint32_t>(0, &root_off) || root_off >= r.len) continue;
    const size_t root = root_off;

    const uint8_t instr = read_u8(r, root, MSG_INSTRUCTION, 0, &err);
    if (err) continue;
    instr_out[bi] = instr;
    if (instr != INSTR_LOCAL_MESSAGE && instr != INSTR_GLOBAL_MESSAGE)
      continue;
    const uint8_t* param;
    int32_t param_len;
    read_blob(r, root, MSG_PARAMETER, &param, &param_len, &err);
    if (err || param != nullptr) continue;  // removal/exotic → object path
    const uint8_t* sender;
    int32_t sender_len;
    read_blob(r, root, MSG_SENDER, &sender, &sender_len, &err);
    if (err || sender == nullptr ||
        !parse_uuid36(sender, sender_len, sender_key + 16 * bi))
      continue;
    const uint8_t* world;
    int32_t wlen;
    read_blob(r, root, MSG_WORLD, &world, &wlen, &err);
    if (err || world == nullptr) continue;
    world_off[bi] = static_cast<int64_t>(world - bufs[bi]);
    world_len_out[bi] = wlen;
    // fields the columnar consumer ignores still classify: the object
    // decoder reads them, so corruption there must route slow
    const uint8_t* mfx;
    int32_t mfxlen;
    read_blob(r, root, MSG_FLEX, &mfx, &mfxlen, &err);
    if (err) continue;
    double mx, my, mz;
    read_vec3(r, root, MSG_POSITION, &mx, &my, &mz, &err);
    if (err) continue;
    {
      size_t rpos = field_pos(r, root, MSG_RECORDS, &err);
      if (err) continue;
      if (rpos != 0) {
        size_t rvec = indirect(r, rpos, &err);
        if (err) continue;
        uint32_t rn;
        if (!r.load<uint32_t>(rvec, &rn)) continue;
        if (!r.in(rvec + 4, static_cast<size_t>(rn) * 4)) continue;
        bool rec_ok = true;
        for (uint32_t i = 0; rec_ok && i < rn; i++) {
          size_t rt = indirect(r, rvec + 4 + 4 * i, &err);
          if (err || !validate_obj(r, rt, &err)) rec_ok = false;
        }
        if (!rec_ok || err) continue;
      }
    }

    // entities vector, read straight off the wire — no object cap
    size_t fpos = field_pos(r, root, MSG_ENTITIES, &err);
    if (err || fpos == 0) continue;
    size_t vec = indirect(r, fpos, &err);
    if (err) continue;
    uint32_t n;
    if (!r.load<uint32_t>(vec, &n) || n == 0) continue;
    if (!r.in(vec + 4, static_cast<size_t>(n) * 4)) continue;
    if (total + static_cast<int64_t>(n) > ent_cap) return WQL_E_CAPACITY;

    bool ok = true;
    for (uint32_t i = 0; ok && i < n; i++) {
      size_t t = indirect(r, vec + 4 + 4 * i, &err);
      if (err) { ok = false; break; }
      const uint8_t* u;
      int32_t ulen;
      read_blob(r, t, OBJ_UUID, &u, &ulen, &err);
      if (err || u == nullptr ||
          !parse_uuid36(u, ulen, uuid_keys + 16 * (total + i))) {
        ok = false;
        break;
      }
      const uint8_t* ew;
      int32_t ewlen;
      read_blob(r, t, OBJ_WORLD, &ew, &ewlen, &err);
      if (err || ew == nullptr) { ok = false; break; }
      // entity world must be the message world (empty = inherit, like
      // `ent.world_name or message.world_name`); anything else keeps
      // the object path's per-entity world semantics
      if (ewlen != 0 &&
          (ewlen != wlen ||
           std::memcmp(ew, world, static_cast<size_t>(wlen)) != 0)) {
        ok = false;
        break;
      }
      double x, y, z;
      if (!read_vec3(r, t, OBJ_POSITION, &x, &y, &z, &err) || err) {
        ok = false;  // position required — the object path raises
        break;
      }
      const uint8_t* dd;
      int32_t ddlen;
      read_blob(r, t, OBJ_DATA, &dd, &ddlen, &err);
      if (err) { ok = false; break; }  // object decoder reads data too
      float* p = pos_out + 3 * (total + i);
      p[0] = static_cast<float>(x);
      p[1] = static_cast<float>(y);
      p[2] = static_cast<float>(z);
      const uint8_t* fx;
      int32_t fxlen;
      read_blob(r, t, OBJ_FLEX, &fx, &fxlen, &err);
      if (err) { ok = false; break; }
      float* v = vel_out + 3 * (total + i);
      if (fx != nullptr && fxlen >= 12) {
        std::memcpy(v, fx, 12);  // 12 LE f32 bytes (host is LE)
        has_vel[total + i] = 1;
      } else {  // absent/short flex = no velocity change
        v[0] = v[1] = v[2] = 0.0f;
        has_vel[total + i] = 0;
      }
    }
    if (!ok) { ent_count[bi] = 0; continue; }
    ent_count[bi] = static_cast<int32_t>(n);
    total += n;
    status[bi] = 1;
  }
  return total;
}

// --------------------------------------- per-cohort frame encoding

namespace {

void unparse_uuid(const uint8_t* b, uint8_t* out36) {
  static const char hexd[] = "0123456789abcdef";
  int j = 0;
  for (int i = 0; i < 16; i++) {
    out36[j++] = hexd[b[i] >> 4];
    out36[j++] = hexd[b[i] & 0xF];
    if (i == 3 || i == 5 || i == 7 || i == 9) out36[j++] = '-';
  }
}

}  // namespace

// Encode n "entity.frame" neighbor frames (LocalMessage, one entity
// each) sharing ONE world in a single native pass — the serialize-once
// cohort encode of entities/plane._build_frames. Frames are
// byte-identical to wql_encode of the equivalent Message (same builder,
// same write order), concatenated into one malloc'd buffer; frame i is
// (*out)[out_off[i] .. +out_len[i]]. Free with wql_buffer_free.
extern "C" int wql_encode_entity_frames(
    const uint8_t* sender_keys, const uint8_t* ent_keys, const double* pos,
    int64_t n, const uint8_t* world, int32_t world_len, uint8_t** out,
    int64_t* out_off, int64_t* out_len) {
  static const uint8_t PARAM[] = "entity.frame";
  std::vector<uint8_t> acc;
  acc.reserve(static_cast<size_t>(n) * 256);
  int64_t cursor = 0;
  for (int64_t i = 0; i < n; i++) {
    uint8_t sender36[36], ent36[36];
    unparse_uuid(sender_keys + 16 * i, sender36);
    unparse_uuid(ent_keys + 16 * i, ent36);
    const double* p = pos + 3 * i;

    Builder b(512);
    WqlObj ent;
    std::memset(&ent, 0, sizeof(ent));
    ent.uuid = ent36;
    ent.uuid_len = 36;
    ent.world = world;
    ent.world_len = world_len;
    ent.has_pos = 1;
    ent.x = p[0];
    ent.y = p[1];
    ent.z = p[2];
    // mirror wql_encode's write order exactly (byte parity)
    size_t entities_vec = write_obj_vector(b, &ent, 1);
    size_t param_off = b.create_blob(PARAM, sizeof(PARAM) - 1, true);
    size_t sender_off = b.create_blob(sender36, 36, true);
    size_t world_off = b.create_blob(world, world_len, true);
    TableBuilder t(b);
    t.field_u8(MSG_INSTRUCTION, INSTR_LOCAL_MESSAGE, 0);
    t.field_uoffset(MSG_PARAMETER, param_off);
    t.field_uoffset(MSG_SENDER, sender_off);
    t.field_uoffset(MSG_WORLD, world_off);
    t.field_uoffset(MSG_ENTITIES, entities_vec);
    b.create_vec3(p[0], p[1], p[2]);
    t.field_struct(MSG_POSITION, 0);
    size_t root = t.end();
    b.prep(std::max<size_t>(b.minalign, 4), 4);
    b.push_uoffset(root);

    const size_t len = b.offset();
    acc.insert(acc.end(), b.store.begin() + b.head,
               b.store.begin() + b.head + len);
    out_off[i] = cursor;
    out_len[i] = static_cast<int64_t>(len);
    cursor += static_cast<int64_t>(len);
  }
  uint8_t* mem = static_cast<uint8_t*>(std::malloc(cursor ? cursor : 1));
  if (!mem) return WQL_E_ALLOC;
  if (cursor) std::memcpy(mem, acc.data(), static_cast<size_t>(cursor));
  *out = mem;
  return WQL_OK;
}

// Encode ONE interest-managed frame (ISSUE 18): a LocalMessage whose
// parameter is the caller's stamped "entity.frame.{full,fullc,delta}"
// string, carrying n entities of one world — live entries as
// positioned entities, departures (tomb[i] != 0) as the same entity
// at its last-known position plus a 1-byte flex tombstone marker
// (short flex is ignored by the velocity decode, so pre-interest
// readers see a harmless entity). The sender is the NIL uuid: these
// frames originate from the server, not a peer. Byte-identical to
// wql_encode / serialize_message of the equivalent Message (same
// builder, same write order, entities field omitted when n == 0 like
// the object encoders omit empty vectors). One malloc'd buffer; free
// with wql_buffer_free.
extern "C" int wql_encode_interest_frame(
    const uint8_t* param, int32_t param_len, const uint8_t* world,
    int32_t world_len, const uint8_t* ent_keys, const double* pos,
    const uint8_t* tomb, int64_t n, uint8_t** out, int64_t* out_len) {
  static const uint8_t NIL36[] = "00000000-0000-0000-0000-000000000000";
  static const uint8_t TOMB1[] = {0};
  if (n < 0 || param == nullptr || world == nullptr || out == nullptr ||
      out_len == nullptr)
    return WQL_E_BOUNDS;

  Builder b(512 + static_cast<size_t>(n) * 160);
  size_t entities_vec = 0;
  if (n > 0) {
    // write_obj_vector without the WQL_MAX_OBJS staging array: frames
    // are chunked by the caller but the encoder itself has no cap
    std::vector<size_t> offs(static_cast<size_t>(n));
    std::vector<uint8_t> keys36(static_cast<size_t>(n) * 36);
    for (int64_t i = 0; i < n; i++) {
      uint8_t* ent36 = keys36.data() + 36 * i;
      unparse_uuid(ent_keys + 16 * i, ent36);
      const double* p = pos + 3 * i;
      WqlObj ent;
      std::memset(&ent, 0, sizeof(ent));
      ent.uuid = ent36;
      ent.uuid_len = 36;
      ent.world = world;
      ent.world_len = world_len;
      ent.has_pos = 1;
      ent.x = p[0];
      ent.y = p[1];
      ent.z = p[2];
      if (tomb != nullptr && tomb[i]) {
        ent.flex = TOMB1;
        ent.flex_len = 1;
      }
      offs[static_cast<size_t>(i)] = write_obj(b, &ent);
    }
    b.prep(4, static_cast<size_t>(n) * 4);
    for (int64_t i = n - 1; i >= 0; i--)
      b.push_uoffset(offs[static_cast<size_t>(i)]);
    b.push_scalar<uint32_t>(static_cast<uint32_t>(n));
    entities_vec = b.offset();
  }
  size_t param_off = b.create_blob(param, param_len, true);
  size_t sender_off = b.create_blob(NIL36, 36, true);
  size_t world_off = b.create_blob(world, world_len, true);
  TableBuilder t(b);
  t.field_u8(MSG_INSTRUCTION, INSTR_LOCAL_MESSAGE, 0);
  t.field_uoffset(MSG_PARAMETER, param_off);
  t.field_uoffset(MSG_SENDER, sender_off);
  t.field_uoffset(MSG_WORLD, world_off);
  if (entities_vec != 0) t.field_uoffset(MSG_ENTITIES, entities_vec);
  size_t root = t.end();
  b.prep(std::max<size_t>(b.minalign, 4), 4);
  b.push_uoffset(root);

  const size_t len = b.offset();
  uint8_t* mem = static_cast<uint8_t*>(std::malloc(len ? len : 1));
  if (!mem) return WQL_E_ALLOC;
  std::memcpy(mem, b.store.data() + b.head, len);
  *out = mem;
  *out_len = static_cast<int64_t>(len);
  return WQL_OK;
}
