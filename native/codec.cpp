// Native WorldQL wire codec: hand-rolled FlatBuffers reader/writer for
// the fixed WorldQLFB schema (reference: worldql_server/src/flatbuffers/
// WorldQLFB_generated.rs; Python twin: worldql_server_tpu/protocol/codec.py).
//
// The reader treats input as untrusted: every load is bounds-checked
// against the buffer (the Rust reference relies on flatbuffers verifier
// semantics; the Python twin bounds-checks likewise). The writer emits
// canonical back-to-front FlatBuffers with per-table vtables (no dedup —
// slightly larger buffers, identical semantics).
//
// C ABI (ctypes consumer: worldql_server_tpu/protocol/native_codec.py):
//   wql_decode(buf, len, WqlMsg* out) -> 0 ok / negative error
//   wql_encode(const WqlMsg* in, uint8_t** out, size_t* out_len) -> 0 ok
//   wql_buffer_free(uint8_t*)
// Strings/bytes in WqlMsg are (pointer, length) views; on decode they
// point into the caller's input buffer (zero-copy).

#include <algorithm>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <vector>

constexpr int32_t WQL_MAX_OBJS = 1024;  // per-message record/entity cap

typedef struct {
  const uint8_t* uuid;  int32_t uuid_len;
  const uint8_t* world; int32_t world_len;
  const uint8_t* data;  int32_t data_len;   // data == NULL → absent
  const uint8_t* flex;  int32_t flex_len;   // flex == NULL → absent
  double x, y, z;
  uint8_t has_pos;
} WqlObj;

typedef struct {
  uint8_t instruction;
  uint8_t replication;
  uint8_t has_pos;
  double x, y, z;
  const uint8_t* parameter; int32_t parameter_len;  // NULL → absent
  const uint8_t* sender;    int32_t sender_len;     // NULL → absent
  const uint8_t* world;     int32_t world_len;      // NULL → absent
  const uint8_t* flex;      int32_t flex_len;       // NULL → absent
  int32_t n_records;
  int32_t n_entities;
  WqlObj records[WQL_MAX_OBJS];
  WqlObj entities[WQL_MAX_OBJS];
} WqlMsg;

enum {
  WQL_OK = 0,
  WQL_E_BOUNDS = -1,    // malformed/truncated buffer
  WQL_E_TOO_MANY = -2,  // > WQL_MAX_OBJS records or entities
  WQL_E_ALLOC = -3,
};

// ---------------------------------------------------------------- reader

namespace {

struct Reader {
  const uint8_t* buf;
  size_t len;

  bool in(size_t pos, size_t n) const {
    return pos <= len && n <= len - pos;
  }
  template <typename T>
  bool load(size_t pos, T* out) const {
    if (!in(pos, sizeof(T))) return false;
    std::memcpy(out, buf + pos, sizeof(T));
    return true;
  }
};

// Field position for a vtable slot; 0 if absent/malformed-absent.
static size_t field_pos(const Reader& r, size_t table, int slot, bool* err) {
  int32_t soff;
  if (!r.load<int32_t>(table, &soff)) { *err = true; return 0; }
  // vtable = table - soff (soffset may be negative)
  int64_t vt = static_cast<int64_t>(table) - soff;
  if (vt < 0 || !r.in(static_cast<size_t>(vt), 4)) { *err = true; return 0; }
  uint16_t vt_size;
  if (!r.load<uint16_t>(static_cast<size_t>(vt), &vt_size)) { *err = true; return 0; }
  size_t entry = static_cast<size_t>(vt) + 4 + 2 * static_cast<size_t>(slot);
  if (4 + 2 * (slot + 1) > vt_size) return 0;  // slot beyond vtable → default
  uint16_t foff;
  if (!r.load<uint16_t>(entry, &foff)) { *err = true; return 0; }
  if (foff == 0) return 0;
  size_t pos = table + foff;
  if (pos >= r.len) { *err = true; return 0; }
  return pos;
}

// Follow a uoffset32 at pos → target position.
static size_t indirect(const Reader& r, size_t pos, bool* err) {
  uint32_t uoff;
  if (!r.load<uint32_t>(pos, &uoff)) { *err = true; return 0; }
  size_t target = pos + uoff;
  if (target >= r.len) { *err = true; return 0; }
  return target;
}

// String/byte-vector at slot: view into the buffer.
static bool read_blob(const Reader& r, size_t table, int slot,
                      const uint8_t** out, int32_t* out_len, bool* err) {
  *out = nullptr; *out_len = 0;
  size_t fpos = field_pos(r, table, slot, err);
  if (*err || fpos == 0) return fpos != 0 && !*err;
  size_t s = indirect(r, fpos, err);
  if (*err) return false;
  uint32_t n;
  if (!r.load<uint32_t>(s, &n)) { *err = true; return false; }
  if (n > r.len || !r.in(s + 4, n)) { *err = true; return false; }
  *out = r.buf + s + 4;
  *out_len = static_cast<int32_t>(n);
  return true;
}

static uint8_t read_u8(const Reader& r, size_t table, int slot,
                       uint8_t dflt, bool* err) {
  size_t fpos = field_pos(r, table, slot, err);
  if (*err || fpos == 0) return dflt;
  uint8_t v;
  if (!r.load<uint8_t>(fpos, &v)) { *err = true; return dflt; }
  return v;
}

static bool read_vec3(const Reader& r, size_t table, int slot,
                      double* x, double* y, double* z, bool* err) {
  size_t fpos = field_pos(r, table, slot, err);
  if (*err || fpos == 0) return false;
  double v[3];
  if (!r.in(fpos, 24)) { *err = true; return false; }
  std::memcpy(v, r.buf + fpos, 24);
  *x = v[0]; *y = v[1]; *z = v[2];
  return true;
}

enum { OBJ_UUID = 0, OBJ_POSITION = 1, OBJ_WORLD = 2, OBJ_DATA = 3,
       OBJ_FLEX = 4 };
enum { MSG_INSTRUCTION = 0, MSG_PARAMETER = 1, MSG_SENDER = 2,
       MSG_WORLD = 3, MSG_REPLICATION = 4, MSG_RECORDS = 5,
       MSG_ENTITIES = 6, MSG_POSITION = 7, MSG_FLEX = 8 };

static bool read_obj(const Reader& r, size_t table, WqlObj* o, bool* err) {
  std::memset(o, 0, sizeof(WqlObj));
  read_blob(r, table, OBJ_UUID, &o->uuid, &o->uuid_len, err);
  if (*err) return false;
  read_blob(r, table, OBJ_WORLD, &o->world, &o->world_len, err);
  if (*err) return false;
  read_blob(r, table, OBJ_DATA, &o->data, &o->data_len, err);
  if (*err) return false;
  read_blob(r, table, OBJ_FLEX, &o->flex, &o->flex_len, err);
  if (*err) return false;
  o->has_pos = read_vec3(r, table, OBJ_POSITION, &o->x, &o->y, &o->z, err)
                   ? 1 : 0;
  return !*err;
}

static int read_obj_vector(const Reader& r, size_t table, int slot,
                           WqlObj* out, int32_t* out_n, bool* err) {
  *out_n = 0;
  size_t fpos = field_pos(r, table, slot, err);
  if (*err) return WQL_E_BOUNDS;
  if (fpos == 0) return WQL_OK;
  size_t vec = indirect(r, fpos, err);
  if (*err) return WQL_E_BOUNDS;
  uint32_t n;
  if (!r.load<uint32_t>(vec, &n)) return WQL_E_BOUNDS;
  if (n > WQL_MAX_OBJS) return WQL_E_TOO_MANY;
  if (!r.in(vec + 4, static_cast<size_t>(n) * 4)) return WQL_E_BOUNDS;
  for (uint32_t i = 0; i < n; i++) {
    size_t t = indirect(r, vec + 4 + 4 * i, err);
    if (*err) return WQL_E_BOUNDS;
    if (!read_obj(r, t, &out[i], err)) return WQL_E_BOUNDS;
  }
  *out_n = static_cast<int32_t>(n);
  return WQL_OK;
}

}  // namespace

extern "C" int wql_decode(const uint8_t* buf, size_t len, WqlMsg* out) {
  Reader r{buf, len};
  bool err = false;
  std::memset(out, 0, offsetof(WqlMsg, records));
  out->n_records = 0;
  out->n_entities = 0;

  uint32_t root_off;
  if (!r.load<uint32_t>(0, &root_off) || root_off >= len) return WQL_E_BOUNDS;
  size_t root = root_off;

  out->instruction = read_u8(r, root, MSG_INSTRUCTION, 0, &err);
  if (err) return WQL_E_BOUNDS;
  out->replication = read_u8(r, root, MSG_REPLICATION, 0, &err);
  if (err) return WQL_E_BOUNDS;
  read_blob(r, root, MSG_PARAMETER, &out->parameter, &out->parameter_len, &err);
  if (err) return WQL_E_BOUNDS;
  read_blob(r, root, MSG_SENDER, &out->sender, &out->sender_len, &err);
  if (err) return WQL_E_BOUNDS;
  read_blob(r, root, MSG_WORLD, &out->world, &out->world_len, &err);
  if (err) return WQL_E_BOUNDS;
  read_blob(r, root, MSG_FLEX, &out->flex, &out->flex_len, &err);
  if (err) return WQL_E_BOUNDS;
  out->has_pos = read_vec3(r, root, MSG_POSITION, &out->x, &out->y, &out->z,
                           &err) ? 1 : 0;
  if (err) return WQL_E_BOUNDS;

  int rc = read_obj_vector(r, root, MSG_RECORDS, out->records,
                           &out->n_records, &err);
  if (rc != WQL_OK || err) return rc != WQL_OK ? rc : WQL_E_BOUNDS;
  rc = read_obj_vector(r, root, MSG_ENTITIES, out->entities,
                       &out->n_entities, &err);
  if (rc != WQL_OK || err) return rc != WQL_OK ? rc : WQL_E_BOUNDS;
  return WQL_OK;
}

// ---------------------------------------------------------------- writer

namespace {

// Back-to-front FlatBuffers builder: offsets are measured from the END
// of the storage; final buffer is the tail slice.
struct Builder {
  std::vector<uint8_t> store;
  size_t head;       // index of first used byte
  size_t minalign = 1;

  explicit Builder(size_t cap = 1024) : store(cap), head(cap) {}

  size_t offset() const { return store.size() - head; }

  void grow(size_t need) {
    if (head >= need) return;
    size_t old_size = store.size();
    size_t new_size = old_size * 2;
    while (new_size - old_size + head < need) new_size *= 2;
    std::vector<uint8_t> bigger(new_size);
    std::memcpy(bigger.data() + (new_size - old_size), store.data(), old_size);
    head += new_size - old_size;
    store.swap(bigger);
  }

  void pad(size_t n) {
    grow(n);
    head -= n;
    std::memset(store.data() + head, 0, n);
  }

  // Align so that after writing `size` bytes, offset() % align == 0.
  void prep(size_t align, size_t extra) {
    if (align > minalign) minalign = align;
    size_t align_size = ((~(offset() + extra)) + 1) & (align - 1);
    pad(align_size);
  }

  void push(const void* src, size_t n) {
    grow(n);
    head -= n;
    std::memcpy(store.data() + head, src, n);
  }

  template <typename T>
  void push_scalar(T v) { push(&v, sizeof(T)); }

  // uoffset32 referencing an object at `target` (offset-from-end).
  void push_uoffset(size_t target) {
    prep(4, 0);
    uint32_t v = static_cast<uint32_t>(offset() + 4 - target);
    push_scalar<uint32_t>(v);
  }

  size_t create_blob(const uint8_t* data, size_t n, bool nul) {
    if (nul) { prep(4, n + 1); uint8_t z = 0; push(&z, 1); }
    else     { prep(4, n); }
    push(data, n);
    push_scalar<uint32_t>(static_cast<uint32_t>(n));
    return offset();
  }

  size_t create_vec3(double x, double y, double z) {
    prep(8, 24);
    double v[3] = {x, y, z};
    push(v, 24);
    return offset();
  }
};

struct TableBuilder {
  Builder& b;
  size_t start;                     // offset() at StartTable
  int max_slot = -1;
  size_t slot_off[16] = {0};        // field offset-from-end per slot

  explicit TableBuilder(Builder& b_) : b(b_), start(b_.offset()) {}

  void track(int slot) {
    slot_off[slot] = b.offset();
    if (slot > max_slot) max_slot = slot;
  }

  void field_u8(int slot, uint8_t v, uint8_t dflt) {
    if (v == dflt) return;
    b.prep(1, 0);
    b.push_scalar<uint8_t>(v);
    track(slot);
  }

  void field_uoffset(int slot, size_t target) {
    b.push_uoffset(target);
    track(slot);
  }

  void field_struct(int slot, size_t target) {
    // Structs are written immediately before; they must be inline at
    // the field position (flatbuffers invariant).
    (void)target;
    track(slot);
  }

  size_t end() {
    // soffset placeholder
    b.prep(4, 0);
    b.push_scalar<int32_t>(0);
    size_t table_start = b.offset();

    int n_slots = max_slot + 1;
    uint16_t vt_size = static_cast<uint16_t>(4 + 2 * n_slots);
    uint16_t tbl_size = static_cast<uint16_t>(table_start - start);

    // vtable entries, last slot first
    for (int i = n_slots - 1; i >= 0; i--) {
      uint16_t entry = slot_off[i]
          ? static_cast<uint16_t>(table_start - slot_off[i]) : 0;
      b.push_scalar<uint16_t>(entry);
    }
    b.push_scalar<uint16_t>(tbl_size);
    b.push_scalar<uint16_t>(vt_size);
    size_t vt = b.offset();

    // patch soffset: vtable relative to table
    int32_t soff = static_cast<int32_t>(vt - table_start);
    size_t table_pos = b.store.size() - table_start;
    std::memcpy(b.store.data() + table_pos, &soff, 4);
    return table_start;
  }
};

static size_t write_obj(Builder& b, const WqlObj* o) {
  size_t uuid_off = b.create_blob(o->uuid, o->uuid_len, true);
  size_t world_off = b.create_blob(o->world, o->world_len, true);
  size_t data_off = o->data ? b.create_blob(o->data, o->data_len, true) : 0;
  size_t flex_off = o->flex ? b.create_blob(o->flex, o->flex_len, false) : 0;

  TableBuilder t(b);
  t.field_uoffset(OBJ_UUID, uuid_off);
  if (o->has_pos) {
    b.create_vec3(o->x, o->y, o->z);
    t.field_struct(OBJ_POSITION, 0);
  }
  t.field_uoffset(OBJ_WORLD, world_off);
  if (data_off) t.field_uoffset(OBJ_DATA, data_off);
  if (flex_off) t.field_uoffset(OBJ_FLEX, flex_off);
  return t.end();
}

static size_t write_obj_vector(Builder& b, const WqlObj* objs, int32_t n) {
  std::vector<size_t> offs(n);
  for (int32_t i = 0; i < n; i++) offs[i] = write_obj(b, &objs[i]);
  b.prep(4, static_cast<size_t>(n) * 4);
  for (int32_t i = n - 1; i >= 0; i--) b.push_uoffset(offs[i]);
  b.push_scalar<uint32_t>(static_cast<uint32_t>(n));
  return b.offset();
}

}  // namespace

extern "C" int wql_encode(const WqlMsg* in, uint8_t** out, size_t* out_len) {
  if (in->n_records > WQL_MAX_OBJS || in->n_entities > WQL_MAX_OBJS)
    return WQL_E_TOO_MANY;
  Builder b(1024);

  size_t records_vec = in->n_records
      ? write_obj_vector(b, in->records, in->n_records) : 0;
  size_t entities_vec = in->n_entities
      ? write_obj_vector(b, in->entities, in->n_entities) : 0;

  size_t param_off = in->parameter
      ? b.create_blob(in->parameter, in->parameter_len, true) : 0;
  size_t sender_off = in->sender
      ? b.create_blob(in->sender, in->sender_len, true) : 0;
  size_t world_off = in->world
      ? b.create_blob(in->world, in->world_len, true) : 0;
  size_t flex_off = in->flex
      ? b.create_blob(in->flex, in->flex_len, false) : 0;

  TableBuilder t(b);
  t.field_u8(MSG_INSTRUCTION, in->instruction, 0);
  if (param_off) t.field_uoffset(MSG_PARAMETER, param_off);
  if (sender_off) t.field_uoffset(MSG_SENDER, sender_off);
  if (world_off) t.field_uoffset(MSG_WORLD, world_off);
  t.field_u8(MSG_REPLICATION, in->replication, 0);
  if (records_vec) t.field_uoffset(MSG_RECORDS, records_vec);
  if (entities_vec) t.field_uoffset(MSG_ENTITIES, entities_vec);
  if (in->has_pos) {
    b.create_vec3(in->x, in->y, in->z);
    t.field_struct(MSG_POSITION, 0);
  }
  if (flex_off) t.field_uoffset(MSG_FLEX, flex_off);
  size_t root = t.end();

  // root uoffset, padded to minalign
  b.prep(std::max<size_t>(b.minalign, 4), 4);
  b.push_uoffset(root);

  size_t n = b.offset();
  uint8_t* mem = static_cast<uint8_t*>(std::malloc(n));
  if (!mem) return WQL_E_ALLOC;
  std::memcpy(mem, b.store.data() + b.head, n);
  *out = mem;
  *out_len = n;
  return WQL_OK;
}

extern "C" void wql_buffer_free(uint8_t* p) { std::free(p); }

extern "C" int wql_max_objs(void) { return WQL_MAX_OBJS; }
