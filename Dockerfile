# syntax=docker/dockerfile:1.3
# Shape mirrors the reference's worldql_server.Dockerfile: a build
# stage producing the native artifacts, a slim non-root runtime, the
# three default service ports exposed.

# ---
# Build Time
FROM python:3.12-slim AS builder

RUN apt-get update && \
  apt-get install --no-install-recommends -y \
    g++ \
    make \
    git && \
  rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY native ./native
COPY worldql_server_tpu ./worldql_server_tpu

# Native wire codec (pure-Python fallback exists, but ship the fast path)
RUN make -C native

RUN pip install --no-cache-dir --prefix=/install .

# ---
# Runtime
FROM python:3.12-slim
WORKDIR /

# Setup non-root user
RUN \
  groupadd -g 1001 worldql && \
  useradd -mu 1001 -g worldql worldql

COPY --from=builder --chown=1001:1001 /install /usr/local
COPY --from=builder --chown=1001:1001 /app/native/libwqlcodec.so /opt/worldql/native/libwqlcodec.so
ENV WQL_NATIVE_CODEC=/opt/worldql/native/libwqlcodec.so

# Stamp the build's git hash for --version (build.rs:4-11 parity);
# docker build --build-arg WQL_GIT_HASH=$(git rev-parse --short HEAD).
# Runtime stage only — a changed hash must not bust the builder's
# dependency-install layer cache.
ARG WQL_GIT_HASH=
ENV WQL_GIT_HASH=${WQL_GIT_HASH}

# Define repo label
ARG GIT_REPO
LABEL org.opencontainers.image.source=${GIT_REPO}

# Expose default ports: ZeroMQ, HTTP, WebSocket
EXPOSE 5555
EXPOSE 8080
EXPOSE 8081

# Records default to an in-container sqlite file the non-root user can
# write; override WQL_STORE_URL for anything durable.
ENV WQL_STORE_URL=sqlite:///home/worldql/worldql.db

# Define user and entrypoint
USER worldql
ENTRYPOINT ["worldql-server-tpu"]
